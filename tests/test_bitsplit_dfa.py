"""Bitsplit-DFA lowering tests (ISSUE 8).

Covers the whole pipeline: subset-construction equivalence against the
bit-parallel NFA oracle (exact when no merging, superset under forced
approximate merging), the three-way kernel differential (numpy oracle /
lax.scan ladder / fused Pallas kernel in interpret mode), end-to-end
verdict bit-identity across PINGOO_DFA=off|auto|force and against the
host interpreter, the state-budget fallback, the artifact-cache
round-trip under the bumped FORMAT_VERSION, the cost-model
forward-compat fix (`_kind_cost`), the lint/metrics registrations, and
the acceptance mutation: breaking prune-only soundness in the
approximate-DFA recheck must surface in the shadow-parity auditor.
"""

import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pingoo_tpu.compiler import compile_ruleset  # noqa: E402
from pingoo_tpu.compiler.nfa import (  # noqa: E402
    MAX_SCAN_BITS,
    build_bank,
    lower_bank_to_dfa,
    scan_bits_needed,
)
from pingoo_tpu.compiler.nfa import scan_numpy as nfa_scan_numpy  # noqa: E402
from pingoo_tpu.compiler.plan import (  # noqa: E402
    DEFAULT_STEP_COSTS,
    DFA_KIND,
    ScanStrategy,
    _kind_cost,
    reselect_scan_strategies,
    select_dfa_strategy,
    select_scan_strategy,
    strategy_steps,
)
from pingoo_tpu.compiler.repat import compile_regex  # noqa: E402
from pingoo_tpu.compiler.repat import literal_pattern  # noqa: E402
from pingoo_tpu.config.schema import Action, RuleConfig  # noqa: E402
from pingoo_tpu.engine import (  # noqa: E402
    RequestTuple,
    encode_requests,
    evaluate_batch,
    make_verdict_fn,
)
from pingoo_tpu.engine.batch import RequestBatch, bucket_arrays  # noqa: E402
from pingoo_tpu.expr import compile_expression  # noqa: E402
from pingoo_tpu.ops.bitsplit_dfa import (  # noqa: E402
    _fused_dfa,
    dfa_row_candidates,
    dfa_scan,
    dfa_skip_hits,
    dfa_to_tables,
)
from pingoo_tpu.ops.bitsplit_dfa import scan_numpy as dfa_scan_numpy  # noqa: E402
from pingoo_tpu.utils.crs import (  # noqa: E402
    LFI_RCE_CORES,
    SQLI_CORES,
    XSS_CORES,
    generate_ruleset,
    generate_traffic,
)

CORPUS_PATTERNS = SQLI_CORES + XSS_CORES + LFI_RCE_CORES


def _corpus_bank_patterns(limit=28):
    """LinearPatterns from the CRS corpus that fit a scan bank — the
    same population compiler/plan feeds build_bank."""
    pats = []
    for src in CORPUS_PATTERNS:
        try:
            alts = compile_regex(src)
        except Exception:
            continue
        for lp in alts:
            if lp.never_match:
                continue
            if scan_bits_needed(lp) > MAX_SCAN_BITS:
                continue
            pats.append(lp)
            if len(pats) >= limit:
                return pats
    return pats


def _random_rows(rng, patterns, n_rows, L):
    """[n, L] data biased to exercise the banks: random noise rows plus
    rows seeded with per-position class members of random patterns."""
    data = np.zeros((n_rows, L), dtype=np.uint8)
    lens = np.zeros((n_rows,), dtype=np.int32)
    for i in range(n_rows):
        kind = rng.random()
        if kind < 0.15:
            lens[i] = 0
            continue
        row = bytearray()
        if kind < 0.45:
            row += bytes(rng.randrange(32, 127)
                         for _ in range(rng.randrange(1, L)))
        else:
            lp = rng.choice(patterns)
            if not lp.anchor_start and rng.random() < 0.5:
                row += bytes(rng.randrange(32, 127)
                             for _ in range(rng.randrange(0, 6)))
            for pos in lp.positions:
                choices = sorted(pos.bytes)
                if not choices:
                    continue
                reps = rng.randrange(0, 3)
                if pos.quant.name == "ONE":
                    reps = 1
                elif pos.quant.name == "PLUS":
                    reps = rng.randrange(1, 3)
                row += bytes(rng.choice(choices) for _ in range(reps))
            if rng.random() < 0.5:
                row += bytes(rng.randrange(32, 127)
                             for _ in range(rng.randrange(0, 6)))
        row = bytes(row)[:L]
        data[i, :len(row)] = np.frombuffer(row, dtype=np.uint8)
        lens[i] = len(row)
    return data, lens


@pytest.fixture(scope="module")
def corpus_bank():
    pats = _corpus_bank_patterns()
    assert len(pats) >= 16
    pats.append(literal_pattern(b"union select", case_insensitive=True))
    return pats, build_bank(pats)


class TestSubsetConstruction:
    def test_exact_dfa_matches_nfa_oracle(self, corpus_bank):
        """Property: with no merging and an ample budget the DFA is
        bit-identical to the bit-parallel NFA on every (row, slot)."""
        pats, bank = corpus_bank
        dfa = lower_bank_to_dfa(pats, state_budget=65536, merge_depths=())
        assert dfa is not None and dfa.exact and dfa.merge_depth == 0
        rng = random.Random(20260804)
        data, lens = _random_rows(rng, pats, 220, 48)
        ref = nfa_scan_numpy(bank, data, lens)
        got = dfa_scan_numpy(dfa, data, lens)
        np.testing.assert_array_equal(got, ref)
        assert ref.any() and not ref.all()  # both polarities exercised

    def test_approximate_dfa_is_sound_superset(self, corpus_bank):
        """Forced merging: the quotient DFA must shrink below the exact
        state count and may only OVER-approximate per slot (candidates
        ⊇ matches) — never lose a hit."""
        pats, bank = corpus_bank
        exact = lower_bank_to_dfa(pats, state_budget=65536, merge_depths=())
        assert exact is not None
        approx = lower_bank_to_dfa(pats, state_budget=exact.num_states - 1,
                                   merge_depths=(8, 4, 2, 1))
        assert approx is not None, "merge ladder should fit under budget"
        assert not approx.exact and approx.merge_depth >= 1
        assert approx.num_states < exact.num_states
        rng = random.Random(77)
        data, lens = _random_rows(rng, pats, 220, 48)
        ref = nfa_scan_numpy(bank, data, lens)
        got = dfa_scan_numpy(approx, data, lens)
        missing = ref & ~got
        assert not missing.any(), "approximate DFA dropped a true match"

    def test_budget_fallback_returns_none(self, corpus_bank):
        pats, _ = corpus_bank
        assert lower_bank_to_dfa(pats, state_budget=2,
                                 merge_depths=()) is None


class TestKernelDifferential:
    def test_three_way_differential(self, corpus_bank):
        """numpy oracle == lax.scan gather ladder == fused Pallas kernel
        (interpret mode — the same kernel program a TPU compiles)."""
        pats, _ = corpus_bank
        dfa = lower_bank_to_dfa(pats, state_budget=65536, merge_depths=())
        tables = dfa_to_tables(dfa)
        rng = random.Random(5150)
        for n_rows, L in ((97, 48), (3, 17), (128, 48)):
            data, lens = _random_rows(rng, pats, n_rows, L)
            ref = dfa_scan_numpy(dfa, data, lens)
            jd, jl = jnp.asarray(data), jnp.asarray(lens)
            got_scan = np.asarray(dfa_scan(tables, jd, jl))
            got_pallas = np.asarray(_fused_dfa(tables, jd, jl,
                                               interpret=True))
            np.testing.assert_array_equal(got_scan, ref)
            np.testing.assert_array_equal(got_pallas, ref)

    def test_skip_hits_and_row_candidates(self, corpus_bank):
        """dfa_skip_hits is the zero-input base; dfa_row_candidates is
        exactly 'hits exceed the base' — the prune-only gate."""
        pats, _ = corpus_bank
        dfa = lower_bank_to_dfa(pats, state_budget=65536, merge_depths=())
        tables = dfa_to_tables(dfa)
        rng = random.Random(31337)
        data, lens = _random_rows(rng, pats, 64, 48)
        jd, jl = jnp.asarray(data), jnp.asarray(lens)
        hits = dfa_scan(tables, jd, jl)
        base = np.asarray(dfa_skip_hits(tables, jl))
        zero_ref = dfa_scan_numpy(dfa, np.zeros_like(data)[:, :0],
                                  np.zeros_like(lens))
        # The base equals a scan of nothing for len-0 rows...
        np.testing.assert_array_equal(base[lens == 0],
                                      zero_ref[lens == 0])
        cand = np.asarray(dfa_row_candidates(tables, hits, jl))
        np.testing.assert_array_equal(
            cand, (np.asarray(hits) & ~base).any(axis=1))


@pytest.fixture(scope="module")
def crs_plan():
    rules, lists = generate_ruleset(120, with_lists=True,
                                    list_sizes=(256, 64))
    plan = compile_ruleset(rules, lists)
    reqs = generate_traffic(160, lists=lists, seed=9, attack_fraction=0.3)
    batch = encode_requests(reqs)
    b2 = RequestBatch(size=batch.size, arrays=bucket_arrays(batch.arrays))
    return rules, lists, plan, reqs, b2


class TestVerdictParity:
    def test_crs_plan_lowers_banks(self, crs_plan):
        _, _, plan, _, _ = crs_plan
        assert plan.stats["dfa_banks"] >= 1
        lowered = [e for e in plan.scan_plans.values() if e.dfa_key]
        assert lowered
        for e in lowered:
            dtab = plan.np_tables[e.dfa_key]
            assert dtab.num_states <= 65536
            assert e.dfa_strategy is not None
            assert e.dfa_strategy.kind == DFA_KIND

    def test_off_auto_force_bit_identical(self, crs_plan, monkeypatch):
        """The acceptance property: verdict matrices bit-identical
        across every PINGOO_DFA mode, composed with every prefilter
        mode, and equal to the host interpreter."""
        from pingoo_tpu.engine.batch import batch_to_contexts
        from pingoo_tpu.engine.verdict import interpret_rules_row

        rules, lists, plan, _, batch = crs_plan
        tables = plan.device_tables()
        outs = {}
        for mode in ("off", "auto", "force"):
            monkeypatch.setenv("PINGOO_DFA", mode)
            outs[mode] = evaluate_batch(plan, make_verdict_fn(plan),
                                        tables, batch, lists)
        np.testing.assert_array_equal(outs["off"], outs["auto"])
        np.testing.assert_array_equal(outs["off"], outs["force"])
        assert outs["off"].any(), "corpus traffic must match something"
        monkeypatch.setenv("PINGOO_DFA", "force")
        for pf_mode in ("off", "banks", "compact"):
            monkeypatch.setenv("PINGOO_PREFILTER", pf_mode)
            got = evaluate_batch(plan, make_verdict_fn(plan),
                                 tables, batch, lists)
            np.testing.assert_array_equal(outs["off"], got)
        contexts = batch_to_contexts(batch, lists)
        for i in (0, 7, 31, 63, 100, 159):
            want = interpret_rules_row(plan, contexts[i])
            np.testing.assert_array_equal(outs["off"][i], want)

    def test_parity_across_seeds_and_odd_batches(self, monkeypatch):
        """Fresh rulesets + odd batch sizes so the compact recheck
        ladder hits its degenerate shapes."""
        for seed, nreq in ((101, 40), (2027, 33)):
            rules, lists = generate_ruleset(60, with_lists=True,
                                            list_sizes=(64, 16))
            reqs = generate_traffic(nreq, lists=lists, seed=seed + 1,
                                    attack_fraction=0.4)
            batch = encode_requests(reqs)
            b2 = RequestBatch(size=batch.size,
                              arrays=bucket_arrays(batch.arrays))
            plan = compile_ruleset(rules, lists)
            outs = {}
            for mode in ("off", "force"):
                monkeypatch.setenv("PINGOO_DFA", mode)
                outs[mode] = evaluate_batch(plan, make_verdict_fn(plan),
                                            plan.device_tables(), b2,
                                            lists)
            np.testing.assert_array_equal(outs["off"], outs["force"])

    def test_pallas_backend_parity(self, crs_plan, monkeypatch):
        rules, lists, plan, _, batch = crs_plan
        tables = plan.device_tables()
        monkeypatch.setenv("PINGOO_DFA", "off")
        want = evaluate_batch(plan, make_verdict_fn(plan), tables, batch,
                              lists)
        monkeypatch.setenv("PINGOO_DFA", "force")
        monkeypatch.setenv("PINGOO_DFA_KERNEL", "pallas")
        got = evaluate_batch(plan, make_verdict_fn(plan), tables, batch,
                             lists)
        np.testing.assert_array_equal(want, got)

    def test_state_budget_fallback_keeps_nfa(self, monkeypatch):
        """PINGOO_DFA_STATES=2: nothing lowers, force mode degrades to
        the plain NFA path bit-identically."""
        monkeypatch.setenv("PINGOO_DFA_STATES", "2")
        rules, lists = generate_ruleset(60, with_lists=True,
                                        list_sizes=(64, 16))
        plan = compile_ruleset(rules, lists)
        assert plan.stats["dfa_banks"] == 0
        assert all(e.dfa_key is None for e in plan.scan_plans.values())
        reqs = generate_traffic(48, lists=lists, seed=3,
                                attack_fraction=0.4)
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        monkeypatch.setenv("PINGOO_DFA", "force")
        got = evaluate_batch(plan, make_verdict_fn(plan),
                             plan.device_tables(), b2, lists)
        monkeypatch.setenv("PINGOO_DFA", "off")
        want = evaluate_batch(plan, make_verdict_fn(plan),
                              plan.device_tables(), b2, lists)
        np.testing.assert_array_equal(want, got)


class TestWindowLowering:
    """ISSUE 8 window-bank lowering: the MXU conv banks' source
    patterns are fixed-shape literal-ish, so the subset construction
    is small and exact — and on the row-work-bound CPU backend the
    DFA gather ladder replaces the conv (engine/verdict
    ._dfa_win_active)."""

    def test_window_banks_lower_exact(self, crs_plan):
        _, _, plan, _, _ = crs_plan
        assert plan.win_dfa, "CRS plan must lower its window banks"
        for key, dkey in plan.win_dfa.items():
            assert key.startswith("win_") and dkey == f"dfa_{key}"
            dtab = plan.np_tables[dkey]
            assert dtab.exact, "window sources are literal-ish"
            assert dtab.num_slots == \
                plan.np_tables[key].kernel.shape[0]

    def test_window_dfa_matches_conv(self, crs_plan):
        """Direct bank-level differential: the lowered DFA's hit
        matrix is bit-identical to the window conv's on real encoded
        traffic, for every lowered field."""
        from pingoo_tpu.ops.window_match import window_hits

        _, _, plan, _, batch = crs_plan
        tables = plan.device_tables()
        for key, dkey in plan.win_dfa.items():
            field = key[len("win_"):]
            data = batch.arrays[f"{field}_bytes"]
            lens = batch.arrays[f"{field}_len"]
            want = np.asarray(window_hits(tables[key],
                                          jnp.asarray(data),
                                          jnp.asarray(lens)))
            got = np.asarray(dfa_scan(tables[dkey],
                                      jnp.asarray(data),
                                      jnp.asarray(lens)))
            np.testing.assert_array_equal(want, got, err_msg=key)

    def test_win_active_policy(self, crs_plan):
        from pingoo_tpu.engine.verdict import _dfa_win_active

        _, _, plan, _, _ = crs_plan
        key = next(iter(plan.win_dfa))
        assert not _dfa_win_active(plan, key, "off")
        assert _dfa_win_active(plan, key, "force")
        on_cpu = jax.default_backend() == "cpu"
        assert _dfa_win_active(plan, key, "auto") == on_cpu
        assert not _dfa_win_active(plan, "win_nope", "force")


class TestPruneOnlyMutation:
    def test_broken_recheck_gate_fails_parity_auditor(self, crs_plan,
                                                      monkeypatch):
        """ISSUE 8 acceptance mutation: if the approximate-DFA recheck
        gate prunes rows it must not (candidates forced empty — the
        prune-only soundness invariant broken), verdicts drop real
        matches and the shadow-parity auditor reports the divergence."""
        import pingoo_tpu.engine.verdict as verdict_mod
        from pingoo_tpu.obs.provenance import ParityAuditor
        from pingoo_tpu.obs.registry import MetricRegistry

        rules, lists, plan, reqs, batch = crs_plan
        approx = [e for e in plan.scan_plans.values()
                  if e.dfa_key and not plan.np_tables[e.dfa_key].exact]
        assert approx, "CRS banks must exercise the approximate path"
        monkeypatch.setenv("PINGOO_DFA", "force")

        def audit(matched):
            aud = ParityAuditor(plan, lists, plane="t_dfa",
                                registry=MetricRegistry(), sample=1.0)
            try:
                assert aud.submit_matrix(reqs, matched)
                assert aud.flush(30)
                return aud.mismatch_total.value
            finally:
                aud.stop()

        clean = evaluate_batch(plan, make_verdict_fn(plan),
                               plan.device_tables(), batch, lists)
        assert audit(clean) == 0

        monkeypatch.setattr(
            verdict_mod, "dfa_row_candidates",
            lambda tables, hits, lengths:
            jnp.zeros((hits.shape[0],), dtype=bool))
        broken = evaluate_batch(plan, make_verdict_fn(plan),
                                plan.device_tables(), batch, lists)
        assert (clean != broken).any(), \
            "the mutation must actually change verdicts"
        assert audit(broken) > 0


class TestCostModelForwardCompat:
    def test_kind_cost_unknown_kind_defaults(self):
        # The satellite fix: a closed cost dict must not KeyError on a
        # kind it predates — schema'd default, then 1.0.
        assert _kind_cost({}, "dfa") == DEFAULT_STEP_COSTS["dfa"]
        assert _kind_cost({"dfa": 0.5}, "dfa") == 0.5
        assert _kind_cost({}, "some_future_kind") == 1.0
        assert _kind_cost({"scan": 2.0}, "some_future_kind", 7.0) == 7.0

    def test_select_with_partial_cost_dict(self):
        class _T:
            halo_ok = False

        # Measured dicts from old bench artifacts carry no "dfa"/"pallas"
        # keys; selection must not raise.
        strat = select_scan_strategy(_T(), costs={"scan": 1.0})
        assert strat.kind in ("scan", "pallas")
        dstrat = select_dfa_strategy(costs={"scan": 1.0})
        assert dstrat.kind == DFA_KIND
        assert dstrat.cost == DEFAULT_STEP_COSTS["dfa"]

    def test_reselect_with_measured_costs_covers_dfa(self, crs_plan):
        import copy

        _, _, plan, _, _ = crs_plan
        clone = copy.deepcopy(plan)
        # A measured dict that predates the dfa kind entirely.
        reselect_scan_strategies(clone, {"scan": 3.0, "pair": 2.0,
                                         "pallas": 1.0,
                                         "pallas_pair": 0.9})
        for key, e in clone.scan_plans.items():
            if e.dfa_key:
                assert e.dfa_strategy is not None
                assert e.dfa_strategy.kind == DFA_KIND
                # Default dfa cost (0.15) still beats the measured best
                # (0.45/iter for pallas_pair), so auto stays on.
                assert e.dfa_auto

    def test_strategy_steps_dfa_is_plain_length(self, crs_plan):
        _, _, plan, _, _ = crs_plan
        for key, e in plan.scan_plans.items():
            if e.split is not None:
                continue
            tab = plan.np_tables[key]
            assert strategy_steps(tab, 64,
                                  ScanStrategy(kind=DFA_KIND)) == 64
            # NFA kinds keep their pass multiplier; the DFA does not.
            assert strategy_steps(tab, 64, ScanStrategy()) \
                == 64 * (1 + tab.extra_passes)


class TestCacheRoundTrip:
    def test_format_version_bumped(self):
        from pingoo_tpu.compiler.cache import FORMAT_VERSION

        # 12: artifacts carry the discharged plan_proof block (ISSUE 18)
        # — a cache hit is also a proof hit.
        assert FORMAT_VERSION == 12

    def test_dfa_tables_survive_cache(self, tmp_path, monkeypatch):
        from pingoo_tpu.compiler.cache import compile_ruleset_cached

        rules, lists = generate_ruleset(60, with_lists=True,
                                        list_sizes=(64, 16))
        cache = str(tmp_path / "cache")
        plan1 = compile_ruleset_cached(rules, lists, cache_dir=cache)
        plan2 = compile_ruleset_cached(rules, lists, cache_dir=cache)
        for key, e1 in plan1.scan_plans.items():
            e2 = plan2.scan_plans[key]
            assert e1.dfa_key == e2.dfa_key
            assert e1.dfa_auto == e2.dfa_auto
            if e1.dfa_key:
                t1 = plan1.np_tables[e1.dfa_key]
                t2 = plan2.np_tables[e2.dfa_key]
                assert t1.num_states == t2.num_states
                assert t1.exact == t2.exact
                np.testing.assert_array_equal(np.asarray(t1.trans_flat),
                                              np.asarray(t2.trans_flat))
        assert plan1.dfa_default_mode == plan2.dfa_default_mode
        reqs = generate_traffic(32, lists=lists, seed=9,
                                attack_fraction=0.4)
        batch = encode_requests(reqs)
        b2 = RequestBatch(size=batch.size,
                          arrays=bucket_arrays(batch.arrays))
        monkeypatch.setenv("PINGOO_DFA", "force")
        m1 = evaluate_batch(plan1, make_verdict_fn(plan1),
                            plan1.device_tables(), b2, lists)
        m2 = evaluate_batch(plan2, make_verdict_fn(plan2),
                            plan2.device_tables(), b2, lists)
        np.testing.assert_array_equal(m1, m2)

    def test_dfa_knobs_enter_fingerprint(self, monkeypatch):
        from pingoo_tpu.compiler.cache import ruleset_fingerprint

        rules = [RuleConfig(name="r0",
                            expression=compile_expression(
                                'http_request.path.contains("/etc")'),
                            actions=(Action.BLOCK,))]
        base = ruleset_fingerprint(rules, {})
        monkeypatch.setenv("PINGOO_DFA_STATES", "99")
        assert ruleset_fingerprint(rules, {}) != base
        monkeypatch.delenv("PINGOO_DFA_STATES")
        monkeypatch.setenv("PINGOO_DFA_LOWER", "0")
        assert ruleset_fingerprint(rules, {}) != base


class TestRegistrations:
    def test_lint_registries_cover_dfa(self):
        from tools.analyze import lint_config

        assert ("pingoo_tpu/ops/bitsplit_dfa.py::dfa_scan"
                in lint_config.TRACED_FUNCTIONS)
        assert ("pingoo_tpu/ops/bitsplit_dfa.py::_fused_dfa"
                in lint_config.TRACED_FUNCTIONS)
        assert ("pingoo_tpu/engine/service.py::"
                "VerdictService._observe_dfa"
                in lint_config.HOT_FUNCTIONS)

    def test_dfa_metrics_schemad_and_wired(self):
        from pingoo_tpu.obs import schema

        assert set(schema.DFA_METRICS) <= schema.all_metric_names()
        assert "pingoo_dfa_banks_total" in schema.DFA_METRICS
        assert "pingoo_dfa_recheck_total" in schema.DFA_METRICS
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ("pingoo_tpu/engine/service.py",
                    "pingoo_tpu/native_ring.py",
                    "docs/OBSERVABILITY.md"):
            with open(os.path.join(repo, rel)) as f:
                src = f.read()
            for name in schema.DFA_METRICS:
                assert name in src, (rel, name)

    def test_service_stats_snapshot_has_dfa_keys(self):
        from pingoo_tpu.engine.service import ServiceStats

        snap = ServiceStats().snapshot()
        assert "dfa_banks" in snap
        assert "dfa_rechecks" in snap

    def test_dispatch_counts_host_static(self, crs_plan, monkeypatch):
        from pingoo_tpu.engine.verdict import dfa_dispatch_counts

        _, _, plan, _, _ = crs_plan
        monkeypatch.setenv("PINGOO_DFA", "off")
        assert dfa_dispatch_counts(plan) == ("off", 0, 0)
        monkeypatch.setenv("PINGOO_DFA", "force")
        mode, banks, rechecks = dfa_dispatch_counts(plan)
        assert mode == "force"
        assert banks == plan.stats["dfa_banks"]
        assert 0 <= rechecks <= banks
        # A pinned NFA strategy override disables auto for the NFA
        # banks (but not force, and not the window-bank DFAs — those
        # are independent of the NFA strategy pin and stay live under
        # auto on the CPU backend).
        monkeypatch.setenv("PINGOO_DFA", "auto")
        monkeypatch.setenv("PINGOO_SCAN_STRATEGY", "pair")
        import jax

        expect_win = (len(getattr(plan, "win_dfa", {}))
                      if jax.default_backend() == "cpu" else 0)
        assert dfa_dispatch_counts(plan)[1] == expect_win
