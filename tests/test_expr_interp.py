"""Interpreter semantics tests — the conformance table for the language.

These encode the parity-oracle semantics the TPU compiler must reproduce
(SURVEY.md §4 test plan item (1): conformance table from docs/rules.md:40-76
plus observed reference semantics).
"""

import math

import pytest

from pingoo_tpu.expr import (
    Context,
    EvalError,
    Ip,
    compile_expression,
    execute_as_bool,
)


def run(src, variables=None):
    return compile_expression(src).execute(Context(variables or {}))


def request_ctx(**over):
    """A context shaped like the reference's (http_listener.rs:238-249)."""
    http_request = {
        "host": "example.com",
        "url": "/index.html?q=1",
        "path": "/index.html",
        "method": "GET",
        "user_agent": "Mozilla/5.0",
    }
    client = {
        "ip": Ip("203.0.113.7"),
        "remote_port": 54321,
        "asn": 64500,
        "country": "FR",
    }
    lists = {
        "blocked_ips": [Ip("127.0.0.1"), Ip("10.0.0.0/8"), Ip("203.0.113.0/24")],
        "blocked_asns": [64500, 64501],
        "bad_paths": ["/admin", "/.env"],
    }
    base = {"http_request": http_request, "client": client, "lists": lists}
    base.update(over)
    return Context(base)


class TestArithmetic:
    @pytest.mark.parametrize(
        "src,expected",
        [
            ("1 + 2", 3),
            ("5 - 8", -3),
            ("6 * 7", 42),
            ("7 / 2", 3),
            ("-7 / 2", -3),  # Rust i64: truncates toward zero
            ("7 % 2", 1),
            ("-7 % 2", -1),  # Rust %: dividend's sign
            ("7 % -2", 1),
            ("1.5 + 2.0", 3.5),
            ("1 + 2.5", 3.5),  # Int/Float promotion
            ("7.0 / 2", 3.5),
            ("-3", -3),
            ("--3", 3),
            ('"a" + "b"', "ab"),
            ("[1] + [2]", [1, 2]),
        ],
    )
    def test_values(self, src, expected):
        assert run(src) == expected

    def test_int_div_by_zero_errors(self):
        with pytest.raises(EvalError, match="division by zero"):
            run("1 / 0")
        with pytest.raises(EvalError, match="division by zero"):
            run("1 % 0")

    def test_float_div_by_zero_is_ieee(self):
        assert run("1.0 / 0.0") == math.inf
        assert run("-1.0 / 0.0") == -math.inf
        assert math.isnan(run("0.0 / 0.0"))

    def test_overflow_errors(self):
        with pytest.raises(EvalError, match="overflow"):
            run("9223372036854775807 + 1")
        with pytest.raises(EvalError, match="overflow"):
            run("-9223372036854775807 - 2")

    def test_type_errors(self):
        with pytest.raises(EvalError):
            run('1 + "a"')
        with pytest.raises(EvalError):
            run("true + true")
        with pytest.raises(EvalError):
            run('-"a"')


class TestFloatEdgeCases:
    def test_inf_modulo_is_nan_not_crash(self):
        assert math.isnan(run("(1.0 / 0.0) % 2.0"))
        assert math.isnan(run("2.0 % 0.0"))
        assert math.isnan(run("(0.0 / 0.0) % 2.0"))

    def test_nan_divided_by_zero_is_nan(self):
        assert math.isnan(run("(0.0 / 0.0) / 0.0"))


class TestIntLiteralRange:
    def test_i64_bounds_writable(self):
        assert run("9223372036854775807") == 2**63 - 1
        assert run("-9223372036854775808") == -(2**63)

    def test_out_of_range_literal_rejected(self):
        from pingoo_tpu.expr import CompileError

        with pytest.raises(CompileError, match="i64 range"):
            compile_expression("9223372036854775808")
        with pytest.raises(CompileError, match="i64 range"):
            compile_expression("-9223372036854775809")
        with pytest.raises(CompileError, match="i64 range"):
            compile_expression("0xFFFFFFFFFFFFFFFF")


class TestComparison:
    @pytest.mark.parametrize(
        "src,expected",
        [
            ("1 == 1", True),
            ("1 != 2", True),
            ("1 == 1.0", True),  # numeric cross-type
            ("1 < 2", True),
            ("2 <= 2", True),
            ("3 > 2.5", True),
            ('"a" < "b"', True),
            ('"abc" == "abc"', True),
            ('"Z" < "a"', True),  # byte-wise ordering
            ("true == true", True),
            ("false != true", True),
            ("[1, 2] == [1, 2]", True),
            ("[1, 2] == [1, 3]", False),
            ("[1] == [1, 2]", False),
            ('{"a": 1} == {"a": 1}', True),
            ('{"a": 1} == {"a": 2}', False),
        ],
    )
    def test_values(self, src, expected):
        assert run(src) is expected

    def test_cross_type_equality_is_error(self):
        with pytest.raises(EvalError):
            run('1 == "1"')
        with pytest.raises(EvalError):
            run("true == 1")

    def test_cross_type_order_is_error(self):
        with pytest.raises(EvalError):
            run('1 < "2"')

    def test_ip_string_equality(self):
        ctx = request_ctx()
        prog = compile_expression('client.ip == "203.0.113.7"')
        assert prog.execute(ctx) is True
        prog = compile_expression('client.ip == "203.0.113.8"')
        assert prog.execute(ctx) is False

    def test_ip_bad_string_is_error(self):
        ctx = request_ctx()
        prog = compile_expression('client.ip == "not-an-ip"')
        with pytest.raises(EvalError):
            prog.execute(ctx)


class TestLogic:
    def test_short_circuit_or_absorbs_right_error(self):
        assert run("true || (1 / 0 == 1)") is True

    def test_short_circuit_and_absorbs_right_error(self):
        assert run("false && (1 / 0 == 1)") is False

    def test_left_error_propagates(self):
        with pytest.raises(EvalError):
            run("(1 / 0 == 1) || true")

    def test_non_bool_operand_is_error(self):
        with pytest.raises(EvalError):
            run("1 && true")
        with pytest.raises(EvalError):
            run("false || 1")
        # Short-circuit: the right operand is never examined.
        assert run("true || 1") is True

    def test_not(self):
        assert run("!false") is True
        assert run("!!true") is True
        with pytest.raises(EvalError):
            run("!1")


class TestStringsAndFunctions:
    def test_string_functions(self):
        ctx = request_ctx()
        assert execute_as_bool(
            compile_expression('http_request.path.starts_with("/index")'), ctx
        )
        assert execute_as_bool(
            compile_expression('http_request.path.ends_with(".html")'), ctx
        )
        assert execute_as_bool(
            compile_expression('http_request.path.contains("ndex")'), ctx
        )
        assert run('"hello".length()') == 5
        assert run('length("hello")') == 5

    def test_length_is_byte_count(self):
        # Canonical strings are latin-1 views of bytes: char count is
        # byte count. A 2-byte UTF-8 sequence arrives as 2 chars.
        assert run('"\\xc3\\xa9".length()') == 2
        assert run('"abc".length()') == 3

    def test_matches(self):
        ctx = request_ctx()
        assert compile_expression(
            'http_request.path.matches("^/index\\\\.")'
        ).execute(ctx) is True
        assert compile_expression(
            'http_request.path.matches("admin")'
        ).execute(ctx) is False

    def test_matches_is_unanchored(self):
        assert run('"xxabcxx".matches("abc")') is True

    def test_bad_regex_is_error(self):
        with pytest.raises(EvalError):
            run('"a".matches("[")')

    def test_array_contains(self):
        assert run('[1, 2, 3].contains(2)') is True
        assert run('["a", "b"].contains("c")') is False

    def test_unknown_function_is_error(self):
        with pytest.raises(EvalError, match="unknown function"):
            run('"a".reverse()')

    def test_arity_errors(self):
        with pytest.raises(EvalError):
            run('"a".contains()')
        with pytest.raises(EvalError):
            run('"a".length(1)')


class TestContextAndLists:
    def test_doc_example_blocked_path(self):
        # docs/rules.md example: http_request.path == "/blocked"
        ctx = request_ctx()
        assert not execute_as_bool(
            compile_expression('http_request.path == "/blocked"'), ctx
        )

    def test_default_waf_rule(self):
        # assets/pingoo.yml basic_waf expression.
        src = (
            'http_request.path.starts_with("/.env") || '
            'http_request.path.starts_with("/.git")'
        )
        prog = compile_expression(src)
        assert not execute_as_bool(prog, request_ctx())
        ctx = request_ctx()
        ctx.variables["http_request"] = dict(
            ctx.variables["http_request"], path="/.env"
        )
        assert execute_as_bool(prog, ctx)

    def test_lists_cidr_contains(self):
        # docs/rules.md:110: lists["blocked_ips"].contains(client.ip)
        prog = compile_expression('lists["blocked_ips"].contains(client.ip)')
        assert execute_as_bool(prog, request_ctx())  # 203.0.113.0/24 hit
        ctx = request_ctx()
        ctx.variables["client"] = dict(ctx.variables["client"], ip=Ip("8.8.8.8"))
        assert not execute_as_bool(prog, ctx)

    def test_int_list(self):
        prog = compile_expression('lists["blocked_asns"].contains(client.asn)')
        assert execute_as_bool(prog, request_ctx())

    def test_missing_list_is_error_hence_no_match(self):
        prog = compile_expression('lists["nope"].contains(client.ip)')
        with pytest.raises(EvalError):
            prog.execute(request_ctx())
        assert execute_as_bool(prog, request_ctx()) is False

    def test_unknown_variable(self):
        with pytest.raises(EvalError):
            run("nope == 1")

    def test_unknown_field(self):
        prog = compile_expression("http_request.nope == 1")
        with pytest.raises(EvalError):
            prog.execute(request_ctx())

    def test_index_errors(self):
        with pytest.raises(EvalError):
            run("[1, 2][5]")
        with pytest.raises(EvalError):
            run("[1, 2][-1]")
        with pytest.raises(EvalError):
            run('{"a": 1}["b"]')
        assert run("[10, 20][1]") == 20
        assert run('{"a": 7}["a"]') == 7


class TestRuleMatching:
    def test_non_bool_result_is_no_match(self):
        # pingoo/rules.rs:47: result must be exactly `true`.
        assert execute_as_bool(compile_expression("1 + 1"), Context()) is False

    def test_error_is_no_match(self):
        # pingoo/rules.rs:41-44: execution error -> false.
        assert execute_as_bool(compile_expression("1 / 0 == 1"), Context()) is False
