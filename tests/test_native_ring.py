"""Native shared-memory ring: C++ <-> Python end-to-end.

Builds the C++ library/loadgen (skipped if no toolchain), then drives
the full transport: the native loadgen produces request tuples into the
ring, the Python sidecar drains batches through the TPU verdict engine
and posts verdicts back, the loadgen checks it got them all.
"""

import json
import os
import subprocess
import threading

import numpy as np
import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.native_ring import Ring, RingSidecar, slots_to_arrays

pytestmark = pytest.mark.skipif(
    not native_ring.ensure_built(), reason="native toolchain unavailable")

LOADGEN = os.path.join(native_ring.NATIVE_DIR, "loadgen")


class TestRingBasics:
    def test_python_roundtrip(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            t1 = ring.enqueue(method=b"GET", host=b"h.test", path=b"/a",
                              url=b"/a?x=1", user_agent=b"UA",
                              ip=bytes(range(16)), port=1234, asn=64500,
                              country=b"FR")
            t2 = ring.enqueue(path=b"/b", user_agent=b"curl")
            assert t1 == 0 and t2 == 1
            slots = ring.dequeue_batch()
            assert len(slots) == 2
            arrays = slots_to_arrays(slots)
            assert bytes(arrays["path_bytes"][0][:2]) == b"/a"
            assert arrays["path_len"][0] == 2
            assert arrays["asn"][0] == 64500
            assert bytes(arrays["country_bytes"][0]) == b"FR"
            assert arrays["remote_port"][0] == 1234
            # verdict roundtrip
            assert ring.post_verdict(t1, 1, 0.9)
            assert ring.post_verdict(t2, 0, 0.1)
            got = {ring.poll_verdict() for _ in range(2)}
            assert {(t1, 1), (t2, 0)} == {(t, a) for t, a, _ in got}
            assert ring.poll_verdict() is None
        finally:
            ring.close()

    def test_padded_url_matches_on_ring_plane(self, tmp_path):
        """A marker past the OLD 512-byte cap must still match through
        the ring (slot caps now equal the 2048-byte device caps), and
        >2048-byte fields set the truncation flag."""
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.verdict import evaluate_batch, first_action
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="r", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.url.contains("evilmarker")'))]
        plan = compile_ruleset(rules, {})

        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            padded = b"/" + b"A" * 900 + b"evilmarker"  # past old 512 cap
            huge = b"/" + b"B" * 3000  # past the 2048 slot cap
            ring.enqueue(url=padded, path=b"/x", user_agent=b"ua")
            ring.enqueue(url=b"/clean", path=b"/x", user_agent=b"ua")
            ring.enqueue(url=huge, path=b"/x", user_agent=b"ua")
            slots = ring.dequeue_batch()
            assert len(slots) == 3
            flags = slots["flags"] & native_ring.SLOT_FLAG_TRUNCATED
            assert flags.tolist() == [0, 0, 1]
            assert slots["url_len"].tolist() == [911, 6, 2048]

            from pingoo_tpu.engine.batch import RequestBatch, bucket_arrays
            from pingoo_tpu.engine.verdict import make_verdict_fn

            batch = RequestBatch(size=3,
                                 arrays=bucket_arrays(slots_to_arrays(slots)))
            matched = evaluate_batch(plan, make_verdict_fn(plan),
                                     plan.device_tables(), batch, {})
            acts = first_action(plan, matched)
            assert acts.tolist() == [1, 0, 0]
        finally:
            ring.close()

    def test_ring_full_and_wraparound(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        try:
            for _ in range(8):
                assert ring.enqueue() is not None
            assert ring.enqueue() is None  # full
            assert len(ring.dequeue_batch()) == 8
            for _ in range(3):  # wraps
                assert ring.enqueue() is not None
            assert len(ring.dequeue_batch()) == 3
        finally:
            ring.close()


class TestNativeEndToEnd:
    def test_loadgen_through_verdict_engine(self, tmp_path):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.utils.crs import generate_ruleset

        rules, lists = generate_ruleset(60, with_lists=True,
                                        list_sizes=(64, 16))
        plan = compile_ruleset(rules, lists)

        ring_path = str(tmp_path / "ring")
        ring = Ring(ring_path, capacity=1024, create=True)
        sidecar = RingSidecar(ring, plan, lists, max_batch=256)
        n = 5000

        worker = threading.Thread(
            target=sidecar.run, kwargs={"max_requests": n}, daemon=True)
        worker.start()
        proc = subprocess.run(
            [LOADGEN, ring_path, str(n), "100"],
            capture_output=True, text=True, timeout=120)
        worker.join(timeout=60)
        ring.close()

        assert proc.returncode == 0, proc.stderr
        result = json.loads(proc.stdout.strip())
        assert result["received"] == n
        # ~10% attacks at permille=100 -> blocks must be in a sane band.
        assert result["blocked"] > n * 0.02, result
        assert result["blocked"] < n * 0.4, result
        assert sidecar.processed == n
