"""Native shared-memory ring: C++ <-> Python end-to-end.

Builds the C++ library/loadgen (skipped if no toolchain), then drives
the full transport: the native loadgen produces request tuples into the
ring, the Python sidecar drains batches through the TPU verdict engine
and posts verdicts back, the loadgen checks it got them all.
"""

import json
import os
import subprocess
import threading

import numpy as np
import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.native_ring import Ring, RingSidecar, slots_to_arrays

pytestmark = pytest.mark.skipif(
    not native_ring.ensure_built(), reason="native toolchain unavailable")

LOADGEN = os.path.join(native_ring.NATIVE_DIR, "loadgen")


def _shm_record(ring, dtype, offset=0):
    """One record decoded from the ring mapping through a mirrored
    dtype, COPIED out — a live np.frombuffer view would pin the mmap's
    exported-buffer count and make Ring.close() raise BufferError."""
    return np.frombuffer(ring.map, dtype=dtype, count=1,
                         offset=offset)[0].copy()


class TestRingBasics:
    def test_python_roundtrip(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            t1 = ring.enqueue(method=b"GET", host=b"h.test", path=b"/a",
                              url=b"/a?x=1", user_agent=b"UA",
                              ip=bytes(range(16)), port=1234, asn=64500,
                              country=b"FR")
            t2 = ring.enqueue(path=b"/b", user_agent=b"curl")
            assert t1 == 0 and t2 == 1
            slots = ring.dequeue_batch()
            assert len(slots) == 2
            arrays = slots_to_arrays(slots)
            assert bytes(arrays["path_bytes"][0][:2]) == b"/a"
            assert arrays["path_len"][0] == 2
            assert arrays["asn"][0] == 64500
            assert bytes(arrays["country_bytes"][0]) == b"FR"
            assert arrays["remote_port"][0] == 1234
            # verdict roundtrip
            assert ring.post_verdict(t1, 1, 0.9)
            assert ring.post_verdict(t2, 0, 0.1)
            got = {ring.poll_verdict() for _ in range(2)}
            assert {(t1, 1), (t2, 0)} == {(t, a) for t, a, _ in got}
            assert ring.poll_verdict() is None
        finally:
            ring.close()

    def test_padded_url_matches_on_ring_plane(self, tmp_path):
        """A marker past the OLD 512-byte cap must still match through
        the ring (slot caps now equal the 2048-byte device caps), and
        >2048-byte fields set the truncation flag."""
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.verdict import evaluate_batch, first_action
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="r", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.url.contains("evilmarker")'))]
        plan = compile_ruleset(rules, {})

        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            padded = b"/" + b"A" * 900 + b"evilmarker"  # past old 512 cap
            huge = b"/" + b"B" * 3000  # past the 2048 slot cap
            ring.enqueue(url=padded, path=b"/x", user_agent=b"ua")
            ring.enqueue(url=b"/clean", path=b"/x", user_agent=b"ua")
            ring.enqueue(url=huge, path=b"/x", user_agent=b"ua")
            slots = ring.dequeue_batch()
            assert len(slots) == 3
            flags = slots["flags"] & native_ring.SLOT_FLAG_TRUNCATED
            assert flags.tolist() == [0, 0, 1]
            assert slots["url_len"].tolist() == [911, 6, 2048]

            from pingoo_tpu.engine.batch import RequestBatch, bucket_arrays
            from pingoo_tpu.engine.verdict import make_verdict_fn

            batch = RequestBatch(size=3,
                                 arrays=bucket_arrays(slots_to_arrays(slots)))
            matched = evaluate_batch(plan, make_verdict_fn(plan),
                                     plan.device_tables(), batch, {})
            acts = first_action(plan, matched)
            assert acts.tolist() == [1, 0, 0]
        finally:
            ring.close()

    def test_ring_full_and_wraparound(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        try:
            for _ in range(8):
                assert ring.enqueue() is not None
            assert ring.enqueue() is None  # full
            assert len(ring.dequeue_batch()) == 8
            for _ in range(3):  # wraps
                assert ring.enqueue() is not None
            assert len(ring.dequeue_batch()) == 3
        finally:
            ring.close()

    def test_abi_roundtrip_header_and_slot_views(self, tmp_path):
        """ISSUE 3 round-trip: C emitter JSON <-> numpy dtypes <->
        pack/unpack of one live slot. The header and slot bytes the C
        side wrote are decoded through the mirrored dtypes ALONE (raw
        buffer views, no FFI) and must read back exactly."""
        from tools.analyze import abi

        golden = abi.load_golden()
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        try:
            hdr = _shm_record(ring, native_ring.RING_HEADER_DTYPE)
            assert int(hdr["magic"]) == native_ring.RING_MAGIC
            assert int(hdr["version"]) == native_ring.RING_FORMAT_VERSION \
                == golden["format_version"]
            assert int(hdr["capacity"]) == 8
            assert int(hdr["request_slot_size"]) == \
                native_ring.REQUEST_SLOT_SIZE
            assert int(hdr["verdict_slot_size"]) == \
                native_ring.VERDICT_SLOT_SIZE

            t = ring.enqueue(method=b"PATCH", host=b"h.example",
                             path=b"/pp", url=b"/pp?q=1",
                             user_agent=b"UA/1", ip=bytes(range(16)),
                             port=4321, asn=64501, country=b"NL")
            raw = _shm_record(ring, native_ring.REQUEST_SLOT_DTYPE,
                              offset=native_ring.RING_HEADER_SIZE)
            assert int(raw["ticket"]) == t
            assert bytes(raw["method"][:raw["method_len"]]) == b"PATCH"
            assert bytes(raw["host"][:raw["host_len"]]) == b"h.example"
            assert bytes(raw["path"][:raw["path_len"]]) == b"/pp"
            assert bytes(raw["url"][:raw["url_len"]]) == b"/pp?q=1"
            assert bytes(raw["user_agent"][:raw["ua_len"]]) == b"UA/1"
            assert bytes(raw["ip"]) == bytes(range(16))
            assert int(raw["remote_port"]) == 4321
            assert int(raw["asn"]) == 64501
            assert bytes(raw["country"]) == b"NL"
            assert int(raw["spill_idx"]) == native_ring.SPILL_NONE
            assert int(raw["enq_ms"]) > 0

            # The dequeued copy equals the raw in-ring bytes field for
            # field (same dtype both sides of the FFI hop).
            slot = ring.dequeue_batch()[0]
            for name in native_ring.REQUEST_SLOT_DTYPE.names:
                assert np.array_equal(slot[name], raw[name]), name

            assert ring.post_verdict(t, 5, 0.25)
            voff = (native_ring.RING_HEADER_SIZE
                    + 8 * native_ring.REQUEST_SLOT_SIZE)
            ver = _shm_record(ring, native_ring.VERDICT_SLOT_DTYPE,
                              offset=voff)
            assert int(ver["ticket"]) == t
            assert int(ver["action"]) == 5
            assert float(ver["bot_score"]) == 0.25
            assert int(ver["seq"]) == 1  # published: seq == pos + 1
        finally:
            ring.close()

    def test_telemetry_block_matches_header_view(self, tmp_path):
        """The ctypes snapshot (Ring.telemetry) and a raw numpy view of
        the v4 header telemetry block must agree, and the counters must
        move through full-ring stalls, drains, and record_waits."""
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        try:
            for _ in range(8):
                assert ring.enqueue() is not None
            assert ring.enqueue() is None  # full-ring stall
            t = ring.telemetry()
            assert t["enqueued"] == 8
            assert t["enqueue_full"] >= 1
            assert t["depth"] == 8
            assert t["depth_hwm"] == 8
            slots = ring.dequeue_batch()
            assert len(slots) == 8
            ring.record_waits(slots["enq_ms"])
            for s in slots:
                assert ring.post_verdict(int(s["ticket"]), 1, 0.0)
            assert not ring.post_verdict(99, 1, 0.0)  # verdict ring full
            t = ring.telemetry()
            assert t["dequeued"] == 8
            assert t["depth"] == 0
            assert t["verdicts_posted"] == 8
            assert t["verdict_post_full"] >= 1
            assert sum(t["wait_hist"]) == 8

            hdr = _shm_record(ring, native_ring.RING_HEADER_DTYPE)
            tel = hdr["telemetry"]
            assert int(tel["enqueued"]) == t["enqueued"]
            assert int(tel["enqueue_full"]) == t["enqueue_full"]
            assert int(tel["dequeued"]) == t["dequeued"]
            assert int(tel["depth_hwm"]) == t["depth_hwm"]
            assert int(tel["verdicts_posted"]) == t["verdicts_posted"]
            assert int(tel["verdict_post_full"]) == t["verdict_post_full"]
            assert [int(x) for x in tel["wait_hist"]] == t["wait_hist"]
        finally:
            ring.close()


class TestNativeEndToEnd:
    def test_loadgen_through_verdict_engine(self, tmp_path):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.utils.crs import generate_ruleset

        rules, lists = generate_ruleset(60, with_lists=True,
                                        list_sizes=(64, 16))
        plan = compile_ruleset(rules, lists)

        ring_path = str(tmp_path / "ring")
        ring = Ring(ring_path, capacity=1024, create=True)
        sidecar = RingSidecar(ring, plan, lists, max_batch=256)
        n = 5000

        worker = threading.Thread(
            target=sidecar.run, kwargs={"max_requests": n}, daemon=True)
        worker.start()
        proc = subprocess.run(
            [LOADGEN, ring_path, str(n), "100"],
            capture_output=True, text=True, timeout=120)
        worker.join(timeout=60)
        ring.close()

        assert proc.returncode == 0, proc.stderr
        result = json.loads(proc.stdout.strip())
        assert result["received"] == n
        # ~10% attacks at permille=100 -> blocks must be in a sane band.
        assert result["blocked"] > n * 0.02, result
        assert result["blocked"] < n * 0.4, result
        assert sidecar.processed == n


class TestMultiRingSidecar:
    """One sidecar draining several worker rings (SO_REUSEPORT per-core
    sharding): verdicts must return on the ring their request came
    from, with first-match actions intact."""

    def test_verdicts_scatter_to_owning_ring(self, tmp_path):
        import threading
        import time

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="blk", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.path.starts_with("/evil")'))]
        plan = compile_ruleset(rules, {})
        rings = [Ring(str(tmp_path / f"r{i}"), capacity=64, create=True)
                 for i in range(3)]
        sidecar = RingSidecar(rings, plan, {}, max_batch=64)
        t = threading.Thread(target=sidecar.run, daemon=True)
        t.start()
        try:
            expect = {}  # ring index -> {ticket: want_block}
            for i, ring in enumerate(rings):
                expect[i] = {}
                for j in range(5):
                    evil = (i + j) % 2 == 0
                    path = b"/evil" if evil else b"/fine"
                    tk = ring.enqueue(path=path, url=path,
                                      user_agent=b"ua", host=b"h")
                    expect[i][tk] = evil
            deadline = time.time() + 30
            got = {i: {} for i in range(3)}
            while time.time() < deadline and any(
                    len(got[i]) < len(expect[i]) for i in range(3)):
                for i, ring in enumerate(rings):
                    v = ring.poll_verdict()
                    if v is not None:
                        got[i][v[0]] = v[1]
                time.sleep(0.01)
            for i in range(3):
                assert set(got[i]) == set(expect[i]), (i, got[i], expect[i])
                for tk, want in expect[i].items():
                    assert (got[i][tk] & 3 == 1) == want, (i, tk, got[i][tk])
        finally:
            sidecar.stop()
            t.join(timeout=10)
            for ring in rings:
                ring.close()


class TestSpillOverflow:
    """v3 ring: >2048-byte url/path rows carry FULL strings in the spill
    area and get exact untruncated verdicts (VERDICT r2 item 5 — the
    reference matches full strings, http_listener.rs:140-141)."""

    def test_spill_roundtrip_and_release(self, tmp_path):
        ring = Ring(str(tmp_path / "r"), capacity=64, create=True)
        try:
            long_url = b"/a" * 1500 + b"NEEDLE" + b"b" * 100  # > 2048
            tk = ring.enqueue(path=b"/p", url=long_url, user_agent=b"ua")
            assert tk is not None
            slots = ring.dequeue_batch(8)
            assert len(slots) == 1
            s = slots[0]
            assert s["flags"] & native_ring.SLOT_FLAG_TRUNCATED
            assert s["spill_idx"] != native_ring.SPILL_NONE
            got = ring.spill_read(int(s["spill_idx"]))
            assert got is not None
            url, path = got
            assert url == long_url and path == b"/p"
            ring.spill_release(int(s["spill_idx"]))
            assert ring.spill_read(int(s["spill_idx"])) is None  # freed
        finally:
            ring.close()

    def test_sidecar_blocks_on_content_past_slot_cap(self, tmp_path):
        import threading
        import time

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="deep", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.url.contains("NEEDLE")'))]
        plan = compile_ruleset(rules, {})
        ring = Ring(str(tmp_path / "r"), capacity=64, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=16)
        t = threading.Thread(target=sidecar.run, daemon=True)
        t.start()
        try:
            # marker entirely PAST the 2048-byte slot view
            deep = b"/" + b"a" * 3000 + b"NEEDLE"
            t_deep = ring.enqueue(path=deep, url=deep, user_agent=b"ua")
            clean = b"/" + b"c" * 3000
            t_clean = ring.enqueue(path=clean, url=clean, user_agent=b"ua")
            got = {}
            deadline = time.time() + 30
            while time.time() < deadline and len(got) < 2:
                v = ring.poll_verdict()
                if v is not None:
                    got[v[0]] = v[1]
                time.sleep(0.01)
            assert got[t_deep] & 3 == 1, got  # blocked on full-string match
            assert got[t_clean] & 3 == 0, got
            assert sidecar.spilled_rows == 2
        finally:
            sidecar.stop()
            t.join(timeout=10)
            ring.close()


class TestSidecarRouting:
    """Sidecar-level service routing: verdict byte bits 3-7 must carry
    the first matching service's order in the REQUEST'S OWN listener
    order (reference selection loop http_listener.rs:266-270; per-
    listener service lists config.rs:241-253). These run _complete
    directly through the drain loop — the unit coverage the round-4
    regression (per-group _host_routes vs flat unpack) lacked."""

    @staticmethod
    def _plan(routes):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="blk", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.path.starts_with("/evil")'))]
        return compile_ruleset(rules, {}, routes=routes)

    @staticmethod
    def _drain(rings, expect_counts, timeout=30):
        import time

        got = [dict() for _ in rings]
        deadline = time.time() + timeout
        while time.time() < deadline and any(
                len(g) < c for g, c in zip(got, expect_counts)):
            for g, ring in zip(got, rings):
                v = ring.poll_verdict()
                if v is not None:
                    g[v[0]] = v[1]
            time.sleep(0.01)
        return got

    def test_route_lane_with_host_fallback_route(self, tmp_path):
        """services= mode: device route + host-interpreted route + catch-
        all, with first-match order across all three."""
        import threading

        from pingoo_tpu.expr import compile_expression

        routes = [
            ("api", compile_expression(
                'http_request.path.starts_with("/api")')),
            # '+' concat is outside the device subset -> host fallback
            ("hostsvc", compile_expression(
                'http_request.host + "" == "hosted.test"')),
            ("web", None),  # no expression -> match-all
        ]
        plan = self._plan(routes)
        assert plan.stats["host_routes"] == 1  # hostsvc fell back
        ring = Ring(str(tmp_path / "r"), capacity=64, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=16,
                              services=["api", "hostsvc", "web"])
        t = threading.Thread(target=sidecar.run, daemon=True)
        t.start()
        try:
            t_api = ring.enqueue(path=b"/api/v1", url=b"/api/v1",
                                 host=b"x.test", user_agent=b"ua")
            t_host = ring.enqueue(path=b"/p", url=b"/p",
                                  host=b"hosted.test", user_agent=b"ua")
            t_web = ring.enqueue(path=b"/p", url=b"/p",
                                 host=b"x.test", user_agent=b"ua")
            t_evil = ring.enqueue(path=b"/evil", url=b"/evil",
                                  host=b"x.test", user_agent=b"ua")
            (got,) = self._drain([ring], [4])
            assert (got[t_api] >> 3) & 31 == 0, got
            assert (got[t_host] >> 3) & 31 == 1, got
            assert (got[t_web] >> 3) & 31 == 2, got
            # blocked AND routed (native plane needs both bits)
            assert got[t_evil] & 3 == 1 and (got[t_evil] >> 3) & 31 == 2
        finally:
            sidecar.stop()
            t.join(timeout=10)
            ring.close()

    def test_ring_services_per_listener_orders(self, tmp_path):
        """ring_services= mode: two rings with DIFFERENT service orders
        route the same request against their OWN listener's table."""
        import threading

        from pingoo_tpu.expr import compile_expression

        routes = [
            ("api", compile_expression(
                'http_request.path.starts_with("/api")')),
            ("web", None),
        ]
        plan = self._plan(routes)
        rings = [Ring(str(tmp_path / f"r{i}"), capacity=64, create=True)
                 for i in range(3)]
        # ring0: [api, web]; ring1: [web] only; ring2: no routing
        sidecar = RingSidecar(
            rings, plan, {}, max_batch=16,
            ring_services=[["api", "web"], ["web"], None])
        t = threading.Thread(target=sidecar.run, daemon=True)
        t.start()
        try:
            tk0 = rings[0].enqueue(path=b"/api/x", url=b"/api/x",
                                   host=b"h", user_agent=b"ua")
            tk1 = rings[1].enqueue(path=b"/api/x", url=b"/api/x",
                                   host=b"h", user_agent=b"ua")
            tk2 = rings[2].enqueue(path=b"/api/x", url=b"/api/x",
                                   host=b"h", user_agent=b"ua")
            g0, g1, g2 = self._drain(rings, [1, 1, 1])
            assert (g0[tk0] >> 3) & 31 == 0  # api is order 0 on ring0
            assert (g1[tk1] >> 3) & 31 == 0  # web is order 0 on ring1
            assert (g2[tk2] >> 3) & 31 == 0  # no group: bits unset
            # same path, ring1 has no api service: routed to web, not
            # ring0's api order — the per-listener property itself.
        finally:
            sidecar.stop()
            t.join(timeout=10)
            for ring in rings:
                ring.close()

    def test_overflow_row_routes_in_ring_group_order(self, tmp_path):
        """A spilled (>2048B) row must route via the host oracle against
        ITS ring's service order, not a global one."""
        import threading

        from pingoo_tpu.expr import compile_expression

        routes = [
            ("deep", compile_expression(
                'http_request.url.contains("NEEDLE")')),
            ("other", compile_expression(
                'http_request.host == "other.test"')),
        ]
        plan = self._plan(routes)
        rings = [Ring(str(tmp_path / f"r{i}"), capacity=64, create=True)
                 for i in range(2)]
        sidecar = RingSidecar(
            rings, plan, {}, max_batch=16,
            ring_services=[["deep", "other"], ["other", "deep"]])
        t = threading.Thread(target=sidecar.run, daemon=True)
        t.start()
        try:
            deep = b"/" + b"a" * 3000 + b"NEEDLE"
            tk0 = rings[0].enqueue(path=deep, url=deep, host=b"h",
                                   user_agent=b"ua")
            tk1 = rings[1].enqueue(path=deep, url=deep, host=b"h",
                                   user_agent=b"ua")
            g0, g1 = self._drain(rings, [1, 1])
            assert (g0[tk0] >> 3) & 31 == 0  # deep at order 0 on ring0
            assert (g1[tk1] >> 3) & 31 == 1  # deep at order 1 on ring1
            assert sidecar.spilled_rows == 2
        finally:
            sidecar.stop()
            t.join(timeout=10)
            for ring in rings:
                ring.close()


class TestServicesTableMarkers:
    """Marker/hostname ambiguity: a TLS server name that equals a
    marker's text must never silently re-tag the hop (internal-token
    leak / TLS-to-cleartext downgrade); the markers themselves are
    identity objects, and the explicit 4-tuple tls form carries
    colliding names safely."""

    def test_hostname_equal_to_marker_text_raises(self, tmp_path):
        from pingoo_tpu.native_ring import write_services_file

        for name in ("internal", "h2-prior-knowledge"):
            with pytest.raises(ValueError, match="collides"):
                write_services_file(
                    str(tmp_path / "t.tbl"),
                    [("svc", [("1.2.3.4", 443, name)])])

    def test_explicit_tls_form_carries_colliding_name(self, tmp_path):
        from pingoo_tpu.native_ring import write_services_file

        p = str(tmp_path / "t.tbl")
        write_services_file(
            p, [("svc", [("1.2.3.4", 443, "tls", "internal")])])
        assert "upstream 1.2.3.4 443 tls internal" in open(p).read()

    def test_marker_objects_still_mark(self, tmp_path):
        from pingoo_tpu.native_ring import H2, INTERNAL, \
            write_services_file

        p = str(tmp_path / "t.tbl")
        write_services_file(
            p, [("svc", [("1.2.3.4", 80, INTERNAL),
                         ("1.2.3.5", 80, H2)])])
        txt = open(p).read()
        assert "upstream 1.2.3.4 80 internal" in txt
        assert "upstream 1.2.3.5 80 h2" in txt
