"""End-to-end host-plane integration: a running server driven over real
sockets against a pong-style upstream (SURVEY.md §4 item 3; reference
pong/pong.rs is the test upstream). Tests run coroutines on the shared
background loop (conftest.LoopRunner) since pytest-asyncio is absent.
"""

import asyncio
import hashlib
import json
import textwrap

import pytest

from pingoo_tpu.config import load_and_validate
from pingoo_tpu.host.server import Server

UA = "Mozilla/5.0 (integration-test)"


async def start_pong(host="127.0.0.1"):
    """Reference pong/pong.rs: a hello-world HTTP upstream."""

    async def handle(reader, writer):
        data = await reader.read(8192)
        first_line = data.split(b"\r\n", 1)[0].decode()
        headers = data.split(b"\r\n\r\n")[0].decode().lower()
        if "upgrade: websocket" in headers:
            # Accept the upgrade and echo raw bytes (the tunnel path).
            writer.write(b"HTTP/1.1 101 Switching Protocols\r\n"
                         b"upgrade: websocket\r\nconnection: Upgrade\r\n"
                         b"sec-websocket-accept: test\r\n\r\n")
            await writer.drain()
            while True:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                writer.write(chunk)
                await writer.drain()
            writer.close()
            return
        body = (f"pong: {first_line}\n"
                f"xff: {'x-forwarded-for' in headers}\n").encode()
        writer.write(
            b"HTTP/1.1 200 OK\r\ncontent-type: text/plain\r\n"
            b"content-length: " + str(len(body)).encode() + b"\r\n\r\n" + body)
        await writer.drain()
        writer.close()

    server = await asyncio.start_server(handle, host, 0)
    return server, server.sockets[0].getsockname()[1]


async def http_get(port, path, headers=None, method="GET", body=b"",
                   host="127.0.0.1"):
    reader, writer = await asyncio.open_connection(host, port)
    hdrs = {"host": "test.local", "user-agent": UA, "connection": "close"}
    hdrs.update(headers or {})
    lines = [f"{method} {path} HTTP/1.1"]
    lines += [f"{k}: {v}" for k, v in hdrs.items() if v is not None]
    if body:
        lines.append(f"content-length: {len(body)}")
    payload = ("\r\n".join(lines) + "\r\n\r\n").encode() + body
    writer.write(payload)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    head, _, resp_body = raw.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    header_map = {}
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.decode("latin-1").partition(":")
        header_map.setdefault(k.strip().lower(), v.strip())
    return status, header_map, resp_body


def write_config(tmp_path, pong_port):
    www = tmp_path / "www"
    www.mkdir(exist_ok=True)
    (www / "index.html").write_text("<h1>welcome</h1>")
    (www / "about.html").write_text("<h1>about</h1>")
    (tmp_path / "blocked_ips.csv").write_text("192.0.2.0/24,test range\n")
    cfg = tmp_path / "pingoo.yml"
    cfg.write_text(textwrap.dedent(f"""
        listeners:
          http:
            address: http://127.0.0.1:0
        services:
          api:
            route: http_request.path.starts_with("/api")
            http_proxy:
              - http://127.0.0.1:{pong_port}
          site:
            static:
              root: {tmp_path}/www
        lists:
          blocked_ips:
            type: Ip
            file: {tmp_path}/blocked_ips.csv
        rules:
          basic_waf:
            expression: http_request.path.starts_with("/.env") || http_request.path.starts_with("/.git")
            actions: [{{action: block}}]
          sqli:
            expression: http_request.url.matches("(?i)union(%20|\\+|\\s)+select")
            actions: [{{action: block}}]
          bot_gate:
            expression: http_request.user_agent.contains("sqlmap")
            actions: [{{action: captcha}}]
    """))
    return cfg


@pytest.fixture(scope="module")
def env(loop_runner, tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("host_e2e")

    async def setup():
        pong, pong_port = await start_pong()
        config = load_and_validate(str(write_config(tmp_path, pong_port)))
        server = Server(
            config,
            use_device=True,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "captcha_jwks.json"),
            tls_dir=str(tmp_path / "tls"),
            enable_docker=False,
        )
        await server.start()
        port = server.http_listeners[0].bound_port
        task = asyncio.create_task(server.serve_forever())
        return pong, server, port, task

    pong, server, port, task = loop_runner.run(setup())

    class Env:
        pass

    e = Env()
    e.port = port
    e.server = server
    e.run = loop_runner.run
    yield e

    async def teardown():
        task.cancel()
        await server.stop()
        pong.close()

    loop_runner.run(teardown())


class TestEndToEnd:
    def test_static_site(self, env):
        status, headers, body = env.run(http_get(env.port, "/"))
        assert status == 200 and b"welcome" in body
        status, _, body = env.run(http_get(env.port, "/about"))
        assert status == 200 and b"about" in body
        status, headers, _ = env.run(http_get(env.port, "/"))
        etag = headers["etag"]
        status, _, _ = env.run(
            http_get(env.port, "/", headers={"if-none-match": etag}))
        assert status == 304

    def test_proxy_with_forwarding_headers(self, env):
        status, headers, body = env.run(http_get(env.port, "/api/hello"))
        assert status == 200
        assert b"pong: GET /api/hello" in body
        assert b"xff: True" in body
        assert headers.get("server") == "pingoo"

    def test_waf_blocks(self, env):
        for path in ("/.env", "/.git/config"):
            status, _, _ = env.run(http_get(env.port, path))
            assert status == 403, path
        # Spaces are illegal in request targets (h11 rejects the request
        # line outright), so real SQLi arrives encoded — match that form.
        for q in ("/api/q?id=1%20UNION%20SELECT%20x",
                  "/api/q?id=1+UNION+SELECT+x"):
            status, _, _ = env.run(http_get(env.port, q))
            assert status == 403, q

    def test_empty_ua_blocked(self, env):
        status, _, _ = env.run(
            http_get(env.port, "/", headers={"user-agent": ""}))
        assert status == 403

    def test_captcha_flow(self, env):
        bot = {"user-agent": "sqlmap/1.8"}
        status, _, body = env.run(http_get(env.port, "/", headers=bot))
        assert status == 403 and b"human" in body

        status, headers, body = env.run(http_get(
            env.port, "/__pingoo/captcha/api/init", method="POST",
            headers=bot))
        assert status == 200
        payload = json.loads(body)
        challenge, difficulty = payload["challenge"], payload["difficulty"]
        cookie = headers["set-cookie"].split(";")[0]
        nonce = 0
        while True:
            digest = hashlib.sha256(
                (challenge + str(nonce)).encode()).hexdigest()
            if digest.startswith("0" * difficulty):
                break
            nonce += 1
        status, headers, body = env.run(http_get(
            env.port, "/__pingoo/captcha/api/verify", method="POST",
            headers=dict(bot, cookie=cookie,
                         **{"content-type": "application/json"}),
            body=json.dumps({"nonce": str(nonce), "hash": digest}).encode()))
        assert status == 200 and json.loads(body)["ok"] is True
        verified_cookie = headers["set-cookie"].split(";")[0]

        status, _, body = env.run(http_get(
            env.port, "/", headers=dict(bot, cookie=verified_cookie)))
        assert status == 200 and b"welcome" in body

    def test_tampered_verified_cookie_serves_challenge(self, env):
        from pingoo_tpu.host.captcha import CAPTCHA_VERIFIED_COOKIE

        status, _, body = env.run(http_get(
            env.port, "/",
            headers={"cookie": f"{CAPTCHA_VERIFIED_COOKIE}=ey.fake.token"}))
        assert status == 403 and b"human" in body

    def test_metrics_endpoint(self, env):
        env.run(http_get(env.port, "/"))
        # JSON (back-compat schema) under Accept: application/json.
        status, _, body = env.run(http_get(
            env.port, "/__pingoo/metrics",
            headers={"accept": "application/json"}))
        assert status == 200
        payload = json.loads(body)
        assert payload["requests"] >= 1
        assert "verdict" in payload
        assert "stages" in payload["verdict"]  # per-stage breakdown
        # Prometheus text is the default exposition.
        status, headers, body = env.run(
            http_get(env.port, "/__pingoo/metrics"))
        assert status == 200
        assert "text/plain" in headers["content-type"]
        text = body.decode()
        assert "pingoo_requests_total" in text
        assert "pingoo_verdict_stage_ms_bucket" in text
        from pingoo_tpu.obs.registry import lint_prometheus_text

        assert lint_prometheus_text(text) == []

    def test_trace_id_header_and_sampled_access_log(self, env, caplog):
        import logging

        from pingoo_tpu.obs.trace import TRACE_HEADER

        listener = env.server.http_listeners[0]
        old_every = listener._access_log.sample_every
        listener._access_log.sample_every = 1  # log every request
        try:
            with caplog.at_level(logging.INFO, logger="pingoo_tpu.access"):
                status, headers, _ = env.run(http_get(env.port, "/"))
        finally:
            listener._access_log.sample_every = old_every
        assert status == 200
        trace_id = headers[TRACE_HEADER]
        assert len(trace_id) == 16
        logged = [r for r in caplog.records
                  if getattr(r, "fields", {}).get("trace_id") == trace_id]
        assert logged, "trace id missing from sampled access log"
        assert logged[0].fields["status"] == 200

    def test_profile_endpoint_bounded_window(self, env):
        status, _, body = env.run(http_get(
            env.port, "/__pingoo/profile?seconds=0.2"))
        payload = json.loads(body)
        if status == 200:
            assert payload["profiling"] is True and payload["dir"]
            # A second capture while the window is live must 409.
            status2, _, body2 = env.run(http_get(
                env.port, "/__pingoo/profile?seconds=0.2"))
            assert status2 == 409
            assert "already active" in json.loads(body2)["error"]
            import time as _time

            _time.sleep(0.4)  # window closes on its own
        else:
            # Profiler unavailable on this backend build: must still be
            # a clean, typed refusal, never a 500.
            assert status == 503 and "error" in payload

    def test_unknown_file_404(self, env):
        status, _, _ = env.run(http_get(env.port, "/nope.xyz"))
        assert status == 404

    def test_traversal_guard(self, env):
        status, _, _ = env.run(http_get(env.port, "/../pingoo.yml"))
        assert status in (403, 404)

    def test_websocket_upgrade_tunnels(self, env):
        """VERDICT r2 item 9: Upgrade requests tunnel raw bytes through
        the proxy after the verdict (reference http_listener.rs:277
        serve_connection_with_upgrades)."""

        async def ws_roundtrip():
            reader, writer = await asyncio.open_connection(
                "127.0.0.1", env.port)
            writer.write(
                b"GET /api/ws HTTP/1.1\r\nhost: test.local\r\n"
                b"user-agent: " + UA.encode() + b"\r\n"
                b"connection: Upgrade\r\nupgrade: websocket\r\n"
                b"sec-websocket-key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
                b"sec-websocket-version: 13\r\n\r\n")
            await writer.drain()
            head = b""
            while b"\r\n\r\n" not in head:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                head += chunk
            assert head.startswith(b"HTTP/1.1 101"), head[:120]
            writer.write(b"\x81\x05hello")
            await writer.drain()
            got = b""
            while len(got) < 7:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                got += chunk
            writer.close()
            return got

        assert env.run(ws_roundtrip()) == b"\x81\x05hello"


class TestTcpProxy:
    def test_tcp_passthrough(self, loop_runner, tmp_path):
        async def flow():
            async def echo(reader, writer):
                data = await reader.read(1024)
                writer.write(b"echo:" + data)
                await writer.drain()
                writer.close()

            upstream = await asyncio.start_server(echo, "127.0.0.1", 0)
            up_port = upstream.sockets[0].getsockname()[1]
            cfg = tmp_path / "pingoo.yml"
            cfg.write_text(textwrap.dedent(f"""
                listeners:
                  tcp:
                    address: tcp://127.0.0.1:0
                services:
                  db:
                    tcp_proxy: [tcp://127.0.0.1:{up_port}]
            """))
            config = load_and_validate(str(cfg))
            server = Server(config, use_device=False, enable_docker=False,
                            geoip_paths=(str(tmp_path / "none"),),
                            captcha_jwks_path=str(tmp_path / "jwks.json"),
                            tls_dir=str(tmp_path / "tls"))
            await server.start()
            port = server.tcp_servers[0].sockets[0].getsockname()[1]
            try:
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", port)
                writer.write(b"hello")
                await writer.drain()
                writer.write_eof()
                data = await reader.read()
                assert data == b"echo:hello"
                writer.close()
            finally:
                await server.stop()
                upstream.close()

        loop_runner.run(flow())


class TestXffTokenTrust:
    """x-forwarded-for trust is TOKEN-BOUND (VERDICT r4 item 5): the
    loopback control plane honors spoofable identity headers only on
    requests carrying the native plane's per-boot x-pingoo-internal
    token — a co-resident process dialing 127.0.0.1 directly cannot
    spoof client identity for IP rules or captcha binding."""

    @pytest.fixture(scope="class")
    def listener(self, loop_runner):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression
        from pingoo_tpu.host.captcha import CaptchaManager
        from pingoo_tpu.host.httpd import HttpListener

        rules = [RuleConfig(
            name="ipblock", actions=(Action.BLOCK,),
            expression=compile_expression('client.ip == "9.9.9.9"'))]
        plan = compile_ruleset(rules, {})

        async def boot(tmpdir):
            svc = VerdictService(plan, {}, use_device=False,
                                 max_wait_us=100)
            lst = HttpListener(
                "ctl", "127.0.0.1", 0, [], svc, {}, plan.rules,
                CaptchaManager(jwks_path=f"{tmpdir}/jwks.json"),
                xff_token="sekrit-token")
            await svc.start()
            await lst.bind()
            asyncio.ensure_future(lst.serve_forever())
            return lst

        import tempfile

        with tempfile.TemporaryDirectory() as tmpdir:
            lst = loop_runner.run(boot(tmpdir))
            yield lst

    def _get(self, loop_runner, port, headers):
        return loop_runner.run(http_get(port, "/x", headers=headers))

    def test_spoofed_xff_without_token_ignored(self, loop_runner, listener):
        status, _, _ = self._get(loop_runner, listener.bound_port,
                                 {"x-forwarded-for": "9.9.9.9"})
        assert status == 404  # rule did NOT match: peer ip was used

    def test_wrong_token_not_trusted(self, loop_runner, listener):
        status, _, _ = self._get(loop_runner, listener.bound_port,
                                 {"x-forwarded-for": "9.9.9.9",
                                  "x-pingoo-internal": "wrong"})
        assert status == 404

    def test_valid_token_binds_client_ip(self, loop_runner, listener):
        status, _, _ = self._get(loop_runner, listener.bound_port,
                                 {"x-forwarded-for": "9.9.9.9",
                                  "x-pingoo-internal": "sekrit-token"})
        assert status == 403  # trusted XFF hit the ip rule
