"""ACME client tests against a local mock RFC 8555 directory.

The mock implements newNonce/newAccount/newOrder/authz/challenge/
finalize/certificate with http-01 validation: it fetches the key
authorization from the client's challenge store exactly the way a CA
would hit /.well-known/acme-challenge/, closing the loop end-to-end
without network egress.
"""

import asyncio
import base64
import datetime
import json
import secrets

import pytest
from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec

from pingoo_tpu.host import jwt as jose
from pingoo_tpu.host.acme import AcmeClient, AcmeManager


class MockCa:
    """Tiny in-process ACME directory."""

    def __init__(self, host="127.0.0.1", challenge_type="http-01"):
        self.host = host
        self.port = None
        self.server = None
        self.challenge_type = challenge_type
        self.orders: dict[str, dict] = {}
        self.authzs: dict[str, dict] = {}
        self.validated_keyauths: list[str] = []
        self.challenge_fetcher = None  # async (token) -> keyauth or None
        # tls-alpn-01: async (domain) -> challenge cert DER or None,
        # i.e. "connect with ALPN acme-tls/1 like a real CA would"
        self.alpn_probe = None
        self.account_thumbprint = None  # RFC 7638, captured at newAccount
        self.ca_key = ec.generate_private_key(ec.SECP256R1())

    def url(self, path):
        return f"http://{self.host}:{self.port}{path}"

    async def start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/dir", self.handle_directory)
        app.router.add_route("HEAD", "/nonce", self.handle_nonce)
        app.router.add_post("/new-account", self.handle_new_account)
        app.router.add_post("/new-order", self.handle_new_order)
        app.router.add_post("/authz/{aid}", self.handle_authz)
        app.router.add_post("/chal/{aid}", self.handle_challenge)
        app.router.add_post("/finalize/{oid}", self.handle_finalize)
        app.router.add_post("/order/{oid}", self.handle_order)
        app.router.add_post("/cert/{oid}", self.handle_cert)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, self.host, 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        self.runner = runner

    async def stop(self):
        await self.runner.cleanup()

    def _nonce_headers(self):
        return {"Replay-Nonce": secrets.token_urlsafe(16)}

    async def handle_directory(self, request):
        from aiohttp import web

        return web.json_response({
            "newNonce": self.url("/nonce"),
            "newAccount": self.url("/new-account"),
            "newOrder": self.url("/new-order"),
        })

    async def handle_nonce(self, request):
        from aiohttp import web

        return web.Response(headers=self._nonce_headers())

    @staticmethod
    async def _jws_payload(request):
        doc = await request.json()
        payload = doc.get("payload", "")
        if not payload:
            return None
        pad = "=" * (-len(payload) % 4)
        return json.loads(base64.urlsafe_b64decode(payload + pad))

    async def handle_new_account(self, request):
        import hashlib

        from aiohttp import web

        doc = await request.json()
        protected = json.loads(base64.urlsafe_b64decode(
            doc["protected"] + "=" * (-len(doc["protected"]) % 4)))
        jwk = protected.get("jwk", {})
        # RFC 7638 thumbprint over the canonical required members.
        canonical = json.dumps(
            {k: jwk[k] for k in sorted(("crv", "kty", "x", "y")) if k in jwk},
            separators=(",", ":"))
        self.account_thumbprint = base64.urlsafe_b64encode(
            hashlib.sha256(canonical.encode()).digest()).rstrip(b"=").decode()
        headers = self._nonce_headers()
        headers["Location"] = self.url("/account/1")
        return web.json_response({"status": "valid"}, status=201,
                                 headers=headers)

    async def handle_new_order(self, request):
        from aiohttp import web

        payload = await self._jws_payload(request)
        oid = secrets.token_hex(4)
        domains = [i["value"] for i in payload["identifiers"]]
        authz_urls = []
        for domain in domains:
            aid = secrets.token_hex(4)
            self.authzs[aid] = {
                "status": "pending", "domain": domain,
                "token": secrets.token_urlsafe(16),
            }
            authz_urls.append(self.url(f"/authz/{aid}"))
        self.orders[oid] = {"status": "pending", "domains": domains,
                            "authz": authz_urls}
        headers = self._nonce_headers()
        headers["Location"] = self.url(f"/order/{oid}")
        return web.json_response({
            "status": "pending",
            "authorizations": authz_urls,
            "finalize": self.url(f"/finalize/{oid}"),
        }, status=201, headers=headers)

    async def handle_authz(self, request):
        from aiohttp import web

        aid = request.match_info["aid"]
        authz = self.authzs[aid]
        return web.json_response({
            "status": authz["status"],
            "identifier": {"type": "dns", "value": authz["domain"]},
            "challenges": [{
                "type": self.challenge_type,
                "url": self.url(f"/chal/{aid}"),
                "token": authz["token"],
            }],
        }, headers=self._nonce_headers())

    async def handle_challenge(self, request):
        from aiohttp import web

        aid = request.match_info["aid"]
        authz = self.authzs[aid]
        if self.challenge_type == "tls-alpn-01":
            ok = await self._validate_tls_alpn(authz)
        else:
            ok = await self._validate_http01(authz)
        authz["status"] = "valid" if ok else "invalid"
        return web.json_response({"status": authz["status"]},
                                 headers=self._nonce_headers())

    async def _validate_http01(self, authz):
        # "Validate" by fetching the key authorization like a real CA.
        keyauth = None
        if self.challenge_fetcher is not None:
            keyauth = await self.challenge_fetcher(authz["token"])
        if keyauth and keyauth.startswith(authz["token"] + "."):
            self.validated_keyauths.append(keyauth)
            return True
        return False

    async def _validate_tls_alpn(self, authz):
        """RFC 8737 §3 validation: fetch the challenge certificate over
        an acme-tls/1 handshake and require a critical acmeIdentifier
        extension carrying SHA256(key authorization)."""
        import hashlib

        from pingoo_tpu.host.acme import ACME_IDENTIFIER_OID

        if self.alpn_probe is None or self.account_thumbprint is None:
            return False
        der = await self.alpn_probe(authz["domain"])
        if der is None:
            return False
        cert = x509.load_der_x509_certificate(der)
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value.get_values_for_type(x509.DNSName)
        if sans != [authz["domain"]]:
            return False
        ext = next((e for e in cert.extensions
                    if e.oid == ACME_IDENTIFIER_OID), None)
        if ext is None or not ext.critical:
            return False
        keyauth = f"{authz['token']}.{self.account_thumbprint}"
        expected = b"\x04\x20" + hashlib.sha256(keyauth.encode()).digest()
        if ext.value.public_bytes() != expected:
            return False
        self.validated_keyauths.append(keyauth)
        return True

    async def handle_finalize(self, request):
        from aiohttp import web

        oid = request.match_info["oid"]
        payload = await self._jws_payload(request)
        order = self.orders[oid]
        csr_der = base64.urlsafe_b64decode(
            payload["csr"] + "=" * (-len(payload["csr"]) % 4))
        csr = x509.load_der_x509_csr(csr_der)
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(csr.subject)
            .issuer_name(x509.Name([]))
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now)
            .not_valid_after(now + datetime.timedelta(days=90))
            .add_extension(
                csr.extensions.get_extension_for_class(
                    x509.SubjectAlternativeName).value, critical=False)
            .sign(self.ca_key, hashes.SHA256())
        )
        order["certificate"] = cert.public_bytes(
            serialization.Encoding.PEM).decode()
        order["status"] = "valid"
        return web.json_response({
            "status": "valid",
            "certificate": self.url(f"/cert/{oid}"),
        }, headers=self._nonce_headers())

    async def handle_order(self, request):
        from aiohttp import web

        oid = request.match_info["oid"]
        order = self.orders[oid]
        body = {"status": order["status"]}
        if "certificate" in order:
            body["certificate"] = self.url(f"/cert/{oid}")
        return web.json_response(body, headers=self._nonce_headers())

    async def handle_cert(self, request):
        from aiohttp import web

        oid = request.match_info["oid"]
        return web.Response(text=self.orders[oid]["certificate"],
                            content_type="application/pem-certificate-chain",
                            headers=self._nonce_headers())


class TestAcme:
    def test_full_order_flow(self, loop_runner, tmp_path):
        async def flow():
            ca = MockCa()
            await ca.start()
            try:
                manager = AcmeManager(
                    str(tmp_path), ["example.test"],
                    directory_url=ca.url("/dir"))

                async def fetch(token):
                    return manager.challenges.get(token)

                ca.challenge_fetcher = fetch
                await manager.renew_all()
                return ca, manager
            finally:
                await ca.stop()
                await manager.client.close()

        ca, manager = loop_runner.run(flow())
        cert_path = tmp_path / "example.test.pem"
        key_path = tmp_path / "example.test.key"
        assert cert_path.exists() and key_path.exists()
        cert = x509.load_pem_x509_certificate(cert_path.read_bytes())
        sans = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        assert sans.get_values_for_type(x509.DNSName) == ["example.test"]
        # Key authorization was published and validated, then cleaned up.
        assert len(ca.validated_keyauths) == 1
        assert manager.challenges == {}
        # Account persisted (versioned doc, acme.rs AcmeConfig::V1).
        doc = json.loads((tmp_path / "acme.json").read_text())
        assert doc["version"] == 1 and doc["account_url"]

    def test_renewal_detection(self, loop_runner, tmp_path):
        from pingoo_tpu.host.tlsmgr import generate_self_signed

        # Fresh cert -> no renewal needed.
        cert, key = generate_self_signed(["good.test"], valid_days=90)
        (tmp_path / "good.test.pem").write_bytes(cert)
        (tmp_path / "good.test.key").write_bytes(key)
        # Expiring cert -> renewal needed.
        cert, key = generate_self_signed(["old.test"], valid_days=5)
        (tmp_path / "old.test.pem").write_bytes(cert)
        manager = AcmeManager(str(tmp_path),
                              ["good.test", "old.test", "missing.test"],
                              directory_url="http://unused/dir")
        needed = manager.domains_needing_certificates()
        assert needed == ["old.test", "missing.test"]

    def test_thumbprint_shape(self):
        key = jose.Key.generate(jose.ALG_ES256)
        tp = jose.jwk_thumbprint(key)
        assert len(tp) == 43  # 32 bytes b64url, no padding
