"""Differential tests for the regex subset compiler + bit-parallel NFA.

Three-way agreement on every (pattern, input) pair:
  Python `re` (bytes mode)  ==  compiler/nfa.simulate  ==  nfa.scan_numpy

This is the core guarantee behind FP/FN parity (BASELINE.md): the device
algebra must be indistinguishable from the reference regex engine on the
supported subset.
"""

import random
import re

import numpy as np
import pytest

from pingoo_tpu.compiler.nfa import build_bank, scan_numpy, simulate
from pingoo_tpu.compiler.repat import Unsupported, compile_regex, literal_pattern

SUPPORTED_PATTERNS = [
    # literals & anchors
    r"abc",
    r"^abc",
    r"abc$",
    r"^abc$",
    r"^$",
    r"a",
    # classes
    r"[abc]x",
    r"[a-z]\d",
    r"[^a-z]+",
    r"\d\d\d",
    r"\w+@\w+",
    r"\s",
    r"a.c",
    r"\.env",
    # quantifiers
    r"ab?c",
    r"ab*c",
    r"ab+c",
    r"a?b?c?d",
    r"^a*$",
    r"a{3}",
    r"a{2,4}b",
    r"a{2,}b",
    r"ba{0,2}",
    # groups / alternation
    r"(abc)",
    r"(?:abc)d",
    r"(a|b)c",
    r"(abc|def)",
    r"(abc|defg)x",
    r"abc|xyz",
    r"^(GET|POST) ",
    r"(ab){1,2}c",
    r"(abc)?d",
    r"x(abc)?$",
    # WAF-style
    r"(?i)union\s+select",
    r"(?i)<script",
    r"\.\./",
    r"etc/passwd",
    r"%3[Cc]script",
    r"eval\(",
    r"[0-9]{1,3}\.[0-9]{1,3}",
    r"(?i)(select|insert|update|delete)\s",
    r"^/(admin|wp-admin|phpmyadmin)",
    r"\x00",
    r"a\|b",
    r"x$|^y",
    r"(a|b|c|d|e|f|g){3}",  # single-char alts merge into a class
    r"(ab|cd){2}",  # repetition rewrite composes with cross product
    # word boundaries (leading/trailing)
    r"\babc",
    r"abc\b",
    r"\babc\b",
    r"\bor\b",
    r"(?i)\bunion\b",
    r"\b\.x",  # non-word first class: requires word char before '.'
    r"x\.\b",  # non-word last class: requires word char after '.'
    r"\bab+\b",
    r"^\babc",
    r"abc\b$",
    r"(?i)\bor\b\s+1=1",  # mid-\b folds away (word before, \s after)
    r"a\bb",  # mid-\b same wordness: statically never matches
    r"x\b\.y",
    r"abc$",  # trailing-newline $ semantics
    r"^abc$",
    r"ab\nc",
    # round-3 compiler extensions (VERDICT r2 item 4)
    r"(abc)+",  # leading repeat truncates by search equivalence
    r"(abc)*x",
    r"(abc|def){1,9}",  # leading bounded repeat truncates to {1}
    r"(\.\./){3,12}etc/(passwd|shadow|group)",  # the CRS LFI staple
    r"x(ab){2,4}y",  # mid-pattern bounded repeat still enumerates
    r"(a|b)+c",  # merged class + unbounded quant
    r"(a|b)*c",
    r"\ba?bc",  # \b next to optional: case-split on presence
    r"\bx?yz",
    r"ab?\bz",
    r"(?i)\bunion\s+select\b\s*\(",  # mid-\b before \s*
    r"(?i)\bexec\b\s*=",
    r"a$\n",  # mid-pattern $: consumes the trailing newline
    r"a$\s*",  # mid-pattern $ with nullable suffix
    r"a$b",  # mid-pattern $: statically never matches
    r"\|\s*id\s*$\s*\(",  # the CRS corpus shape (never matches)
    r"\|\s*id\s*$\d",
    r"foo\Z",  # absolute end anchor
    r"^foo\Z",
    r"\Afoo",
    r"a\Z",
    r"foo\b\Z",  # trailing boundary at absolute end (word last class)
    r"x=\b\Z",  # non-word last class: statically never matches
    r"x\.\b$",
]

UNSUPPORTED_PATTERNS = [
    r"x(abc)+",  # unbounded multi-char group repeat with a prefix
    r"a(?=b)",  # lookahead
    r"(a)\1",  # backreference
    r"a{1,90}" * 2,  # expansion too large even for the multi-word cap
    r"\b(a|\s)x",  # boundary before mixed word/non-word class
    r"a*?",  # lazy
    r"(?s)a.c",  # dotall
    r"(?P<x>ab)",  # named group
    r"x(abc|def){1,20}y",  # cross-product expansion too large mid-pattern
    r"foo\z",  # re.error in the oracle — must not compile on device
    r"a\Bb",  # non-boundary assertion
]


def gen_inputs(rng: random.Random, n: int = 60) -> list[bytes]:
    corpus = [
        b"", b"a", b"abc", b"xabcx", b"ABC", b"aaab", b"abbbc", b"ac",
        b"abcabc", b"union  select", b"UNION SELECT", b"/admin/x",
        b"GET /index.html", b"POST /login", b"../../../etc/passwd",
        b"<script>alert(1)</script>", b"%3Cscript%3E", b"eval(atob(x))",
        b"10.0.0.1", b"999.999", b"word boundary", b"a|b", b"x", b"y",
        b"xyz", b"def", b"defgx", b"abcd", b"\x00\x01", b"aa", b"aaaa",
        b"abc\n", b"abc\n\n", b"\n", b"a\n", b"ab\ncd", b"xabc\n",
        b"abc", b" abc ", b"xabc", b"abcx", b" abc", b"abc ", b"or",
        b"for", b"orb", b" or 1=1", b"union select", b"UNION ALL",
        b".x", b"a.x", b" .x", b"x.", b"x.a", b"x. ", b"ab", b"abb ",
    ]
    alphabet = b"abcdefgxyz0123456789 ./<>%|$^\\()[]{}\x00\nABC"
    for _ in range(n):
        k = rng.randint(0, 24)
        corpus.append(bytes(rng.choice(alphabet) for _ in range(k)))
    return corpus


@pytest.mark.parametrize("pattern", SUPPORTED_PATTERNS)
def test_three_way_agreement(pattern):
    rng = random.Random(hash(pattern) & 0xFFFF)
    alts = compile_regex(pattern)
    gold = re.compile(pattern.encode("utf-8"))
    inputs = gen_inputs(rng)

    # simulate() agreement
    for data in inputs:
        want = gold.search(data) is not None
        got = any(simulate(lp, data) for lp in alts)
        assert got == want, f"simulate {pattern!r} on {data!r}: {got} != {want}"

    # scan_numpy() agreement (pad to fixed length)
    bank = build_bank(alts)
    L = max(1, max(len(d) for d in inputs))
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lengths = np.zeros(len(inputs), dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
        lengths[i] = len(d)
    out = scan_numpy(bank, mat, lengths)  # [B, P] per-alternative
    for i, data in enumerate(inputs):
        want = gold.search(data) is not None
        got = bool(out[i].any())
        assert got == want, f"scan {pattern!r} on {data!r}: {got} != {want}"


@pytest.mark.parametrize("pattern", UNSUPPORTED_PATTERNS)
def test_unsupported_rejected(pattern):
    with pytest.raises(Unsupported):
        compile_regex(pattern)


def test_literal_pattern_contains():
    lp = literal_pattern(b"needle")
    assert simulate(lp, b"find the needle here")
    assert not simulate(lp, b"nothing")
    lp_ci = literal_pattern(b"NeEdLe", case_insensitive=True)
    assert simulate(lp_ci, b"xxNEEDLExx")
    assert simulate(lp_ci, b"xxneedlexx")


def test_random_patterns_fuzz():
    """Randomized supported-pattern generator vs re, via all three engines."""
    rng = random.Random(1234)
    atoms = ["a", "b", "c", "x", r"\d", r"\w", r"[a-c]", r"[^ab]", "."]
    quants = ["", "", "", "?", "*", "+", "{2}", "{1,3}"]
    for trial in range(150):
        n = rng.randint(1, 6)
        parts = []
        for _ in range(n):
            parts.append(rng.choice(atoms) + rng.choice(quants))
        pattern = "".join(parts)
        if rng.random() < 0.25:
            pattern = "^" + pattern
        if rng.random() < 0.25:
            pattern = pattern + "$"
        try:
            alts = compile_regex(pattern)
        except Unsupported:
            continue
        gold = re.compile(pattern.encode())
        inputs = gen_inputs(rng, n=25)
        bank = build_bank(alts)
        L = max(1, max(len(d) for d in inputs))
        mat = np.zeros((len(inputs), L), dtype=np.uint8)
        lengths = np.zeros(len(inputs), dtype=np.int32)
        for i, d in enumerate(inputs):
            mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
            lengths[i] = len(d)
        out = scan_numpy(bank, mat, lengths)
        for i, data in enumerate(inputs):
            want = gold.search(data) is not None
            got_sim = any(simulate(lp, data) for lp in alts)
            got_scan = bool(out[i].any())
            assert got_sim == want, (
                f"simulate {pattern!r} on {data!r}: {got_sim} != {want}")
            assert got_scan == want, (
                f"scan {pattern!r} on {data!r}: {got_scan} != {want}")


def test_multi_pattern_bank_packing():
    """Many patterns packed into shared words keep independent verdicts."""
    patterns = []
    sources = [r"abc", r"^xyz", r"\d+$", r"a.c", r"(?i)select", r"x{2,3}",
               r"[a-f]+z", r"qq", r"^/api/", r"\.php$"]
    per_pattern = []
    for src in sources:
        alts = compile_regex(src)
        per_pattern.append((src, len(alts)))
        patterns.extend(alts)
    bank = build_bank(patterns)
    # All of these are small; they must share words.
    assert bank.num_words < len(patterns)

    rng = random.Random(7)
    inputs = gen_inputs(rng, n=40) + [b"/api/v1/x.php", b"selectx", b"12",
                                       b"aXc", b"ffz", b"xxx"]
    L = max(len(d) for d in inputs)
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lengths = np.array([len(d) for d in inputs], dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
    out = scan_numpy(bank, mat, lengths)
    col = 0
    for src, n_alts in per_pattern:
        gold = re.compile(src.encode())
        got = out[:, col : col + n_alts].any(axis=1)
        for i, d in enumerate(inputs):
            assert got[i] == (gold.search(d) is not None), (
                f"bank {src!r} on {d!r}")
        col += n_alts


# -- multi-word patterns (>31 positions, cross-word carry) -------------------

MULTIWORD_CASES = [
    # (pattern, targeted inputs) — truth always comes from `re`.
    ("x" * 40,
     [b"x" * 40, b"x" * 39, b"pad" + b"x" * 40 + b"tail", b"x" * 80,
      b"x" * 20 + b"y" + b"x" * 19]),
    ("k" * 80,
     [b"k" * 80, b"k" * 79, b"z" * 30 + b"k" * 80]),
    ("z" * 126,  # at the MAX_SCAN_BITS cap
     [b"z" * 126, b"z" * 125, b"q" + b"z" * 126]),
    (r"<svg[^>]{0,40}onload",  # CRS-style opt run crossing a word boundary
     [b"<svg onload", b"<svg " + b"a" * 40 + b"onload",
      b"<svg " + b"a" * 41 + b"onload", b"<svg>onload",
      b"<svg" + b"b" * 36 + b"onload", b"onload<svg"]),
    ("(?i)" + "union" * 8,  # case-insensitive 40-position literal
     [b"union" * 8, b"UNION" * 8, b"UnIoN" * 8, b"union" * 7,
      b"x" + b"uNion" * 8 + b"y"]),
    ("^" + "a" * 50,  # anchored: injection only at t == 0
     [b"a" * 50, b"a" * 49, b"b" + b"a" * 50, b"a" * 60]),
    ("b" * 45 + "$",  # $: accept positions near the span end
     [b"b" * 45, b"b" * 45 + b"\n", b"b" * 45 + b"x", b"x" + b"b" * 45,
      b"b" * 44]),
    (r"\b" + "w" * 40 + r"\b",  # boundary alternatives in a span
     [b"w" * 40, b" " + b"w" * 40 + b" ", b"3" + b"w" * 40,
      b"w" * 41, b"-" + b"w" * 40 + b"."]),
    ("a" * 30 + "[0-9]{0,30}" + "b" * 30,  # opt run mid-span
     [b"a" * 30 + b"b" * 30, b"a" * 30 + b"123" + b"b" * 30,
      b"a" * 30 + b"1" * 30 + b"b" * 30, b"a" * 30 + b"1" * 31 + b"b" * 30,
      b"a" * 29 + b"b" * 30]),
    ("p" * 31 + "q?" * 10 + "r",  # opt run straddling the 32-bit boundary
     [b"p" * 31 + b"r", b"p" * 31 + b"q" * 10 + b"r",
      b"p" * 31 + b"q" * 4 + b"r", b"p" * 31 + b"q" * 11 + b"r",
      b"p" * 30 + b"r"]),
    ("m" * 20 + "n+" + "o" * 20,  # self-loop feeding a cross-word advance
     [b"m" * 20 + b"n" + b"o" * 20, b"m" * 20 + b"n" * 40 + b"o" * 20,
      b"m" * 20 + b"o" * 20]),
    ("e{0,60}f",  # 60-bit pure-optional run: crosses two boundaries
     [b"f", b"e" * 60 + b"f", b"ef", b"e" * 61 + b"f", b"g" * 5 + b"f",
      b"e" * 59]),
    ("(longfirstalternative[0-9]{5,10}|second[a-z]{20,30}tail)",
     [b"longfirstalternative12345", b"longfirstalternative1234",
      b"second" + b"q" * 20 + b"tail", b"second" + b"q" * 31 + b"tail",
      b"secondtail", b"x longfirstalternative1234567890 y"]),
]


def _scan_bank(patterns, inputs):
    bank = build_bank(patterns)
    L = max(1, max(len(d) for d in inputs))
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lengths = np.array([len(d) for d in inputs], dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
    return bank, scan_numpy(bank, mat, lengths)


@pytest.mark.parametrize("pattern,targeted", MULTIWORD_CASES,
                         ids=[p[:34] for p, _ in MULTIWORD_CASES])
def test_multiword_differential(pattern, targeted):
    """re == simulate == scan_numpy on >1-word patterns, each packed
    alone (dedicated span; bank must report carry)."""
    alts = compile_regex(pattern)
    rng = random.Random(hash(pattern) & 0xFFFF)
    inputs = list(targeted) + gen_inputs(rng, n=30)
    bank, out = _scan_bank(alts, inputs)
    assert bank.has_carry, "multi-word pattern must produce a carry span"
    gold = re.compile(pattern.encode())
    for i, data in enumerate(inputs):
        want = gold.search(data) is not None
        got_sim = any(simulate(lp, data) for lp in alts)
        got_scan = bool(out[i].any())
        assert got_sim == want, (
            f"simulate {pattern!r} on {data!r}: {got_sim} != {want}")
        assert got_scan == want, (
            f"scan {pattern!r} on {data!r}: {got_scan} != {want}")


def test_multiword_mixed_bank():
    """Single-word and multi-word patterns coexist in one bank without
    cross-talk; single-word words keep carry disabled."""
    sources = [r"abc", "x" * 40, r"^/api/", r"<svg[^>]{0,40}onload",
               r"\.php$", "k" * 80, r"(?i)select"]
    patterns, spans = [], []
    for src in sources:
        alts = compile_regex(src)
        spans.append((len(patterns), len(patterns) + len(alts)))
        patterns.extend(alts)
    rng = random.Random(5)
    inputs = (gen_inputs(rng, n=40) +
              [b"x" * 40, b"k" * 80, b"<svg " + b"a" * 30 + b"onload",
               b"/api/abc.php", b"x" * 39 + b"SELECT"])
    bank, out = _scan_bank(patterns, inputs)
    assert bank.has_carry
    for (lo, hi), src in zip(spans, sources):
        gold = re.compile(src.encode())
        got = out[:, lo:hi].any(axis=1)
        for i, d in enumerate(inputs):
            assert got[i] == (gold.search(d) is not None), (src, d)


def test_multiword_fuzz():
    """Randomized long-pattern generator: differential vs re across the
    one/two/three/four-word footprint range."""
    rng = random.Random(20260729)
    atoms = ["a", "b", "x", r"\d", r"[a-c]", r"[^ab]", "."]
    quants = ["", "", "", "?", "*", "+", "{2}", "{1,3}", "{0,9}"]
    tested = 0
    for trial in range(200):
        n = rng.randint(10, 40)
        parts = []
        for _ in range(n):
            parts.append(rng.choice(atoms) + rng.choice(quants))
        pattern = "".join(parts)
        if rng.random() < 0.2:
            pattern = "^" + pattern
        if rng.random() < 0.2:
            pattern = pattern + "$"
        try:
            alts = compile_regex(pattern)
        except Unsupported:
            continue
        from pingoo_tpu.compiler.nfa import WORD_BITS, scan_bits_needed
        if max(scan_bits_needed(lp) for lp in alts) <= WORD_BITS:
            continue  # only exercise the multi-word path here
        tested += 1
        gold = re.compile(pattern.encode())
        inputs = gen_inputs(rng, n=15)
        # Bias toward near-matches: mutate a sampled matching prefix.
        alphabet = b"abx0123456789c "
        for _ in range(10):
            k = rng.randint(20, 70)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        bank, out = _scan_bank(alts, inputs)
        for i, data in enumerate(inputs):
            want = gold.search(data) is not None
            got_sim = any(simulate(lp, data) for lp in alts)
            got_scan = bool(out[i].any())
            assert got_sim == want, (pattern, data, "simulate")
            assert got_scan == want, (pattern, data, "scan")
    assert tested >= 30, f"only {tested} multi-word patterns exercised"


def test_span_tail_sharing_fuzz():
    """Randomized mixed banks: small patterns first-fit into the free
    tails of multi-word spans' last words — differential vs re across
    many random packings (the guard-bit/carry safety argument under
    fuzz, not just one fixed layout)."""
    rng = random.Random(20260729)
    small = [r"abc", r"qq", r"\.php$", r"x{2,3}y", r"^/a", r"zz\b",
             r"[0-9]{3}", r"mn?o"]
    tested_shared = 0
    for trial in range(60):
        sources = []
        # 1-3 multiword patterns + 3-6 small ones, shuffled
        for _ in range(rng.randint(1, 3)):
            n = rng.randint(35, 100)
            ch = rng.choice("kwyz")
            sources.append(ch * n)
        sources += rng.sample(small, rng.randint(3, 6))
        rng.shuffle(sources)
        patterns, spans = [], []
        for src in sources:
            alts = compile_regex(src)
            spans.append((src, len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        bank = build_bank(patterns)
        # Count banks where a SMALL pattern's accept actually landed in
        # a dedicated span word (tail sharing really happened).
        from pingoo_tpu.compiler.nfa import scan_bits_needed

        col = 0
        shared_here = False
        for lp in patterns:
            n_accepts = bank.slots[col].accepts
            if (scan_bits_needed(lp) <= 32 and len(n_accepts) == 1
                    and bank.dedicated[n_accepts[0][0]]):
                shared_here = True
            col += 1
        tested_shared += 1 if shared_here else 0
        inputs = gen_inputs(rng, n=20)
        for src in sources:
            ch = src[0]
            if src == ch * len(src):
                inputs.append(ch.encode() * len(src))
                inputs.append(ch.encode() * (len(src) - 1))
                inputs.append(b"PAD" + ch.encode() * len(src))
        _, out = _scan_bank(patterns, inputs)
        for (src, lo, hi) in spans:
            gold = re.compile(src.encode())
            got = out[:, lo:hi].any(axis=1)
            for i, d in enumerate(inputs):
                assert got[i] == (gold.search(d) is not None), (src, d)
    # Shuffled order means sharing only occurs when a span precedes
    # the small patterns and no earlier shared word fits them first.
    assert tested_shared >= 10


class TestPackedScan:
    """The packed multi-bank scan (ops/nfa_scan.packed_scan_states) must
    be bit-identical to the per-field scan in every packing mode — it is
    the serving hot path behind engine/verdict (VERDICT r2 item 3)."""

    # url/path share L=64 so the length/batch fusion paths actually
    # fuse; user_agent's L=128 exercises the mixed-length handling.
    BANKS = {
        "nfa_url": ([r"(?i)union\s+select", r"\.\./", r"a{40,60}b",
                     r"etc/passwd", r"(?i)<script", r"x{30}y{30}z{30}"], 64),
        "nfa_path": ([r"^/(admin|wp-admin)", r"\babc\b", r"eval\(",
                      r"%3[Cc]", r"k{50,90}"], 64),
        "nfa_user_agent": ([r"(?i)sqlmap", r"curl/\d"], 128),
    }

    def _build(self, rng):
        import jax

        banks = {}
        datas = {}
        lens = {}
        spans = {}
        from pingoo_tpu.ops.nfa_scan import bank_to_tables

        B = 17
        alphabet = b"abckwxyz/.<%3CeUNIONunion select admivp-qsqlmap0(d"
        for key, (sources, L) in self.BANKS.items():
            patterns = []
            spans[key] = []
            for src in sources:
                alts = compile_regex(src)
                spans[key].append((src, len(patterns), len(patterns) + len(alts)))
                patterns.extend(alts)
            bank = build_bank(patterns)
            banks[key] = bank_to_tables(bank)
            data = np.zeros((B, L), dtype=np.uint8)
            ln = np.zeros(B, dtype=np.int32)
            specials = [b"", b"union  select", b"../..", b"a" * 45 + b"b",
                        b"/admin/x", b"xabc ", b"eval(", b"sqlmap",
                        b"curl/8", b"k" * 60, b"etc/passwd"]
            for i in range(B):
                if i < len(specials):
                    raw = specials[i][:L]
                else:
                    raw = bytes(rng.choice(alphabet)
                                for _ in range(rng.randint(0, L)))
                data[i, :len(raw)] = np.frombuffer(raw, dtype=np.uint8)
                ln[i] = len(raw)
            datas[key] = data
            lens[key] = ln
        return banks, datas, lens, spans

    @pytest.mark.parametrize("mode",
                             ["field", "length", "fill", "single", "batch"])
    def test_modes_match_per_field_scan(self, mode):
        import jax

        from pingoo_tpu.ops.nfa_scan import (extract_slots, nfa_scan,
                                             packed_scan_states)

        rng = random.Random(99)
        banks, datas, lens, spans = self._build(rng)
        states = jax.jit(
            lambda b, d, n: packed_scan_states(b, d, n, mode=mode)
        )(banks, datas, lens)
        for key in banks:
            want = np.asarray(nfa_scan(banks[key], datas[key], lens[key]))
            got = np.asarray(
                extract_slots(banks[key], states[key], lens[key]))
            np.testing.assert_array_equal(want, got, err_msg=f"{mode}:{key}")
            # and against the re oracle end to end
            for src, lo, hi in spans[key]:
                gold = re.compile(src.encode())
                for i in range(datas[key].shape[0]):
                    d = bytes(datas[key][i, :lens[key][i]])
                    assert bool(got[i, lo:hi].any()) == (
                        gold.search(d) is not None), (mode, key, src, d)

    def test_pack_groups_respect_lane_cap_and_atoms(self):
        from pingoo_tpu.ops.nfa_scan import LANE_GROUP, pack_scan_groups

        rng = random.Random(5)
        banks, datas, lens, _ = self._build(rng)
        sizes = [(k, datas[k].shape[1], banks[k].atoms) for k in sorted(banks)]
        for mode in ("length", "fill"):
            groups = pack_scan_groups(sizes, mode)
            covered = {k: [] for k in banks}
            for Lg, members in groups:
                w = sum(m.w_hi - m.w_lo for m in members)
                assert w <= LANE_GROUP
                for m in members:
                    covered[m.key].append((m.w_lo, m.w_hi))
                    assert Lg >= datas[m.key].shape[1]
                    # member boundaries sit on atom starts: the first
                    # word of a member never carries from its neighbor
                    starts = {lo for lo, _ in banks[m.key].atoms}
                    assert m.w_lo in starts
            for k, pieces in covered.items():
                pieces.sort()
                assert pieces[0][0] == 0
                assert pieces[-1][1] == banks[k].num_words
                for (_, hi), (lo2, _) in zip(pieces, pieces[1:]):
                    assert hi == lo2


class TestHaloSplitScan:
    """Within-device sequence split (ops/nfa_scan.halo_split_scan) must
    be bit-identical to the plain scan for bounded-memory banks."""

    def _bank(self, sources):
        from pingoo_tpu.ops.nfa_scan import bank_to_tables

        patterns = []
        spans = []
        for src in sources:
            alts = compile_regex(src)
            spans.append((src, len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        return bank_to_tables(build_bank(patterns)), spans

    @pytest.mark.parametrize("k", [2, 4, 8])
    def test_matches_plain_scan(self, k):
        import jax

        from pingoo_tpu.ops.nfa_scan import halo_split_scan, nfa_scan

        # Bounded-memory shapes only (no bare x+/x* self-loops): every
        # rep bit must be a sticky accumulator for halo_ok.
        tables, spans = self._bank([
            r"(?i)sqlmap", r"curl/\d", r"^Mozilla", r"bot$", r"\bzgrab\b",
            r"python-requests", r"(?i)nikto", r"a{6}b",
        ])
        assert tables.halo_ok
        L = 128
        if tables.max_footprint > L // k:
            pytest.skip("halo exceeds chunk at this k (guarded by "
                        "halo_split_k in the dispatcher)")
        rng = random.Random(31)
        B = 23
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        specials = [b"", b"sqlmap", b"x" * 100 + b"sqlmap", b"curl/8",
                    b"Mozilla/5.0", b"xMozilla", b"somebot", b"bot x",
                    b"zgrab scan", b"aaaaaab", b"x" * 120 + b"aaaaaab",
                    b"python-requests/2", b"NIKTO" + b"y" * 90 + b"bot"]
        alphabet = b"abcxyz/.Mozilsqmpurt -50bgN"
        for i in range(B):
            raw = specials[i] if i < len(specials) else bytes(
                rng.choice(alphabet) for _ in range(rng.randint(0, L)))
            raw = raw[:L]
            data[i, :len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            lens[i] = len(raw)
        want = np.asarray(nfa_scan(tables, data, lens))
        got = np.asarray(jax.jit(
            lambda t, d, n: halo_split_scan(t, d, n, k))(tables, data, lens))
        np.testing.assert_array_equal(want, got)
        # and vs the re oracle
        for src, lo, hi in spans:
            gold = re.compile(src.encode())
            for i in range(B):
                d = bytes(data[i, :lens[i]])
                assert bool(got[i, lo:hi].any()) == (
                    gold.search(d) is not None), (k, src, d)

    def test_split_k_selection(self):
        from pingoo_tpu.ops.nfa_scan import halo_split_k

        tables, _ = self._bank([r"(?i)sqlmap", r"curl/\d"])
        assert tables.halo_ok
        H = tables.max_footprint
        k = halo_split_k(tables, 128)
        assert k > 1 and H <= 128 // k and 128 // k + H < 128
        # unbounded-memory bank never splits
        nt, _ = self._bank([r"a+b"])
        assert not nt.halo_ok
        assert halo_split_k(nt, 128) == 1


class TestLookupStrategies:
    """Every byte-class lookup strategy of scan_chunk (take / cls_take /
    oh_f32 — see ops/nfa_scan._bc_fn) must produce bit-identical
    verdicts: they are alternate lowerings of the same [256, W] table
    lookup, selected for speed per backend (the one-hot f32 matmul is
    exact because the table splits into u16 halves, all < 2^16 and so
    exactly representable in f32, and a one-hot row selects exactly one
    table row)."""

    SOURCES = [
        r"(?i)union\s+select", r"\.\./", r"a{10,20}b", r"etc/passwd",
        r"(?i)<script", r"%3[Cc]", r"eval\(", r"curl/\d", r"bot$",
        r"\bzgrab\b", r"^/(admin|wp-admin)",
    ]

    def _build(self):
        from pingoo_tpu.ops.nfa_scan import bank_to_tables

        patterns = []
        for src in self.SOURCES:
            patterns.extend(compile_regex(src))
        return bank_to_tables(build_bank(patterns))

    def _data(self, rng, B, L):
        alphabet = b"abcxyz/.<%3CeUNIONunion selectadmivp-curl8botzgra("
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        specials = [b"", b"union  select", b"../", b"a" * 15 + b"b",
                    b"/etc/passwd", b"<SCRIPT>", b"%3c", b"eval(",
                    b"curl/7", b"xbot", b"zgrab ", b"/admin"]
        for i in range(B):
            raw = specials[i] if i < len(specials) else bytes(
                rng.choice(alphabet) for _ in range(rng.randint(0, L)))
            raw = raw[:L]
            data[i, :len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            lens[i] = len(raw)
        return data, lens

    def test_class_compression_is_sound(self):
        tables = self._build()
        bt = np.asarray(tables.byte_table)
        cls_map = np.asarray(tables.cls_map)
        cls_table = np.asarray(tables.cls_table)
        # cls_table[cls_map] reconstructs the byte table exactly
        np.testing.assert_array_equal(cls_table[cls_map], bt)
        # u16 halves recombine to the class table exactly
        u16 = np.asarray(tables.cls_u16)
        W = bt.shape[1]
        lo = u16[:, :W].astype(np.uint32)
        hi = u16[:, W:].astype(np.uint32)
        np.testing.assert_array_equal(lo | (hi << 16), cls_table)

    @pytest.mark.parametrize("lookup", ["cls_take", "oh_f32", "pair"])
    def test_lookup_matches_take(self, lookup):
        import jax

        from pingoo_tpu.ops.nfa_scan import nfa_scan

        tables = self._build()
        rng = random.Random(7)
        data, lens = self._data(rng, 41, 96)
        want = np.asarray(nfa_scan(tables, data, lens, lookup="take"))
        got = np.asarray(jax.jit(
            lambda t, d, n: nfa_scan(t, d, n, lookup=lookup)
        )(tables, data, lens))
        np.testing.assert_array_equal(want, got)

    def test_pair_mode_odd_chunk_composition(self):
        """Pair mode over ODD-width chunks composed ring-style: the
        synthetic pad byte of a non-final chunk sits at a global
        position the NEXT chunk owns, so it must be structurally
        skipped — the live gate alone cannot kill it (its t is inside
        the request length). Splits a 66-byte field at column 33 and
        matches a pattern straddling the cut."""
        import jax

        from pingoo_tpu.ops.nfa_scan import (bank_to_tables, extract_slots,
                                             init_scan_state, nfa_scan,
                                             scan_chunk)

        patterns = []
        for src in (r"needle", r"cut{2}ing", r"bot$"):
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))
        rng = random.Random(3)
        B, L, cut = 37, 66, 33
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        specials = [b"x" * 30 + b"needle" + b"y" * 20,  # straddles col 33
                    b"x" * 28 + b"cutting",
                    b"z" * 60 + b"bot", b"needle", b"bot"]
        alphabet = b"needlcutibot xyz"
        for i in range(B):
            raw = specials[i] if i < len(specials) else bytes(
                rng.choice(alphabet) for _ in range(rng.randint(0, L)))
            raw = raw[:L]
            data[i, :len(raw)] = np.frombuffer(raw, dtype=np.uint8)
            lens[i] = len(raw)
        want = np.asarray(nfa_scan(tables, data, lens, lookup="take"))

        def chunked(t, d, n):
            st = init_scan_state(B, t.opt.shape[0])
            st = scan_chunk(t, d[:, :cut], n, st, 0, lookup="pair")
            st = scan_chunk(t, d[:, cut:], n, st, cut, lookup="pair")
            return extract_slots(t, st, n)

        got = np.asarray(jax.jit(chunked)(tables, data, lens))
        np.testing.assert_array_equal(want, got)

    @pytest.mark.parametrize("lookup", ["cls_take", "oh_f32", "pair"])
    def test_lookup_matches_take_in_halo_split(self, lookup):
        """halo_split_scan routes through scan_chunk with per-row
        t_offsets; the lookup strategies must compose with that path."""
        import jax

        from pingoo_tpu.ops.nfa_scan import (halo_split_scan, nfa_scan,
                                             scan_chunk)

        from pingoo_tpu.ops.nfa_scan import bank_to_tables

        patterns = []
        for src in (r"(?i)sqlmap", r"curl/\d", r"bot$", r"a{6}b"):
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))
        assert tables.halo_ok
        rng = random.Random(13)
        data, lens = self._data(rng, 19, 128)
        want = np.asarray(nfa_scan(tables, data, lens, lookup="take"))
        # monkeypatch-free: force the strategy through scan_chunk's env
        # default by calling with explicit chunks via halo_split_scan,
        # whose scan_chunk call uses the module default. Instead compare
        # the strategy directly on the split layout by patching the
        # default for the duration.
        import pingoo_tpu.ops.nfa_scan as mod
        old = mod.LOOKUP_MODE
        mod.LOOKUP_MODE = lookup
        try:
            got = np.asarray(jax.jit(
                lambda t, d, n: halo_split_scan(t, d, n, 2))(
                    tables, data, lens))
        finally:
            mod.LOOKUP_MODE = old
        np.testing.assert_array_equal(want, got)
