"""Differential tests for the regex subset compiler + bit-parallel NFA.

Three-way agreement on every (pattern, input) pair:
  Python `re` (bytes mode)  ==  compiler/nfa.simulate  ==  nfa.scan_numpy

This is the core guarantee behind FP/FN parity (BASELINE.md): the device
algebra must be indistinguishable from the reference regex engine on the
supported subset.
"""

import random
import re

import numpy as np
import pytest

from pingoo_tpu.compiler.nfa import build_bank, scan_numpy, simulate
from pingoo_tpu.compiler.repat import Unsupported, compile_regex, literal_pattern

SUPPORTED_PATTERNS = [
    # literals & anchors
    r"abc",
    r"^abc",
    r"abc$",
    r"^abc$",
    r"^$",
    r"a",
    # classes
    r"[abc]x",
    r"[a-z]\d",
    r"[^a-z]+",
    r"\d\d\d",
    r"\w+@\w+",
    r"\s",
    r"a.c",
    r"\.env",
    # quantifiers
    r"ab?c",
    r"ab*c",
    r"ab+c",
    r"a?b?c?d",
    r"^a*$",
    r"a{3}",
    r"a{2,4}b",
    r"a{2,}b",
    r"ba{0,2}",
    # groups / alternation
    r"(abc)",
    r"(?:abc)d",
    r"(a|b)c",
    r"(abc|def)",
    r"(abc|defg)x",
    r"abc|xyz",
    r"^(GET|POST) ",
    r"(ab){1,2}c",
    r"(abc)?d",
    r"x(abc)?$",
    # WAF-style
    r"(?i)union\s+select",
    r"(?i)<script",
    r"\.\./",
    r"etc/passwd",
    r"%3[Cc]script",
    r"eval\(",
    r"[0-9]{1,3}\.[0-9]{1,3}",
    r"(?i)(select|insert|update|delete)\s",
    r"^/(admin|wp-admin|phpmyadmin)",
    r"\x00",
    r"a\|b",
    r"x$|^y",
    r"(a|b|c|d|e|f|g){3}",  # single-char alts merge into a class
    r"(ab|cd){2}",  # repetition rewrite composes with cross product
    # word boundaries (leading/trailing)
    r"\babc",
    r"abc\b",
    r"\babc\b",
    r"\bor\b",
    r"(?i)\bunion\b",
    r"\b\.x",  # non-word first class: requires word char before '.'
    r"x\.\b",  # non-word last class: requires word char after '.'
    r"\bab+\b",
    r"^\babc",
    r"abc\b$",
    r"(?i)\bor\b\s+1=1",  # mid-\b folds away (word before, \s after)
    r"a\bb",  # mid-\b same wordness: statically never matches
    r"x\b\.y",
    r"abc$",  # trailing-newline $ semantics
    r"^abc$",
    r"ab\nc",
]

UNSUPPORTED_PATTERNS = [
    r"(abc)+",  # unbounded multi-char group repeat
    r"a(?=b)",  # lookahead
    r"(a)\1",  # backreference
    r"a{1,50}" * 2,  # expansion too large
    r"\b(a|\s)x",  # boundary before mixed word/non-word class
    r"\ba?bc",  # boundary before optional position
    r"a*?",  # lazy
    r"(?s)a.c",  # dotall
    r"(?P<x>ab)",  # named group
    r"(abc|def){1,9}",  # cross-product expansion too large
]


def gen_inputs(rng: random.Random, n: int = 60) -> list[bytes]:
    corpus = [
        b"", b"a", b"abc", b"xabcx", b"ABC", b"aaab", b"abbbc", b"ac",
        b"abcabc", b"union  select", b"UNION SELECT", b"/admin/x",
        b"GET /index.html", b"POST /login", b"../../../etc/passwd",
        b"<script>alert(1)</script>", b"%3Cscript%3E", b"eval(atob(x))",
        b"10.0.0.1", b"999.999", b"word boundary", b"a|b", b"x", b"y",
        b"xyz", b"def", b"defgx", b"abcd", b"\x00\x01", b"aa", b"aaaa",
        b"abc\n", b"abc\n\n", b"\n", b"a\n", b"ab\ncd", b"xabc\n",
        b"abc", b" abc ", b"xabc", b"abcx", b" abc", b"abc ", b"or",
        b"for", b"orb", b" or 1=1", b"union select", b"UNION ALL",
        b".x", b"a.x", b" .x", b"x.", b"x.a", b"x. ", b"ab", b"abb ",
    ]
    alphabet = b"abcdefgxyz0123456789 ./<>%|$^\\()[]{}\x00\nABC"
    for _ in range(n):
        k = rng.randint(0, 24)
        corpus.append(bytes(rng.choice(alphabet) for _ in range(k)))
    return corpus


@pytest.mark.parametrize("pattern", SUPPORTED_PATTERNS)
def test_three_way_agreement(pattern):
    rng = random.Random(hash(pattern) & 0xFFFF)
    alts = compile_regex(pattern)
    gold = re.compile(pattern.encode("utf-8"))
    inputs = gen_inputs(rng)

    # simulate() agreement
    for data in inputs:
        want = gold.search(data) is not None
        got = any(simulate(lp, data) for lp in alts)
        assert got == want, f"simulate {pattern!r} on {data!r}: {got} != {want}"

    # scan_numpy() agreement (pad to fixed length)
    bank = build_bank(alts)
    L = max(1, max(len(d) for d in inputs))
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lengths = np.zeros(len(inputs), dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
        lengths[i] = len(d)
    out = scan_numpy(bank, mat, lengths)  # [B, P] per-alternative
    for i, data in enumerate(inputs):
        want = gold.search(data) is not None
        got = bool(out[i].any())
        assert got == want, f"scan {pattern!r} on {data!r}: {got} != {want}"


@pytest.mark.parametrize("pattern", UNSUPPORTED_PATTERNS)
def test_unsupported_rejected(pattern):
    with pytest.raises(Unsupported):
        compile_regex(pattern)


def test_literal_pattern_contains():
    lp = literal_pattern(b"needle")
    assert simulate(lp, b"find the needle here")
    assert not simulate(lp, b"nothing")
    lp_ci = literal_pattern(b"NeEdLe", case_insensitive=True)
    assert simulate(lp_ci, b"xxNEEDLExx")
    assert simulate(lp_ci, b"xxneedlexx")


def test_random_patterns_fuzz():
    """Randomized supported-pattern generator vs re, via all three engines."""
    rng = random.Random(1234)
    atoms = ["a", "b", "c", "x", r"\d", r"\w", r"[a-c]", r"[^ab]", "."]
    quants = ["", "", "", "?", "*", "+", "{2}", "{1,3}"]
    for trial in range(150):
        n = rng.randint(1, 6)
        parts = []
        for _ in range(n):
            parts.append(rng.choice(atoms) + rng.choice(quants))
        pattern = "".join(parts)
        if rng.random() < 0.25:
            pattern = "^" + pattern
        if rng.random() < 0.25:
            pattern = pattern + "$"
        try:
            alts = compile_regex(pattern)
        except Unsupported:
            continue
        gold = re.compile(pattern.encode())
        inputs = gen_inputs(rng, n=25)
        bank = build_bank(alts)
        L = max(1, max(len(d) for d in inputs))
        mat = np.zeros((len(inputs), L), dtype=np.uint8)
        lengths = np.zeros(len(inputs), dtype=np.int32)
        for i, d in enumerate(inputs):
            mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
            lengths[i] = len(d)
        out = scan_numpy(bank, mat, lengths)
        for i, data in enumerate(inputs):
            want = gold.search(data) is not None
            got_sim = any(simulate(lp, data) for lp in alts)
            got_scan = bool(out[i].any())
            assert got_sim == want, (
                f"simulate {pattern!r} on {data!r}: {got_sim} != {want}")
            assert got_scan == want, (
                f"scan {pattern!r} on {data!r}: {got_scan} != {want}")


def test_multi_pattern_bank_packing():
    """Many patterns packed into shared words keep independent verdicts."""
    patterns = []
    sources = [r"abc", r"^xyz", r"\d+$", r"a.c", r"(?i)select", r"x{2,3}",
               r"[a-f]+z", r"qq", r"^/api/", r"\.php$"]
    per_pattern = []
    for src in sources:
        alts = compile_regex(src)
        per_pattern.append((src, len(alts)))
        patterns.extend(alts)
    bank = build_bank(patterns)
    # All of these are small; they must share words.
    assert bank.num_words < len(patterns)

    rng = random.Random(7)
    inputs = gen_inputs(rng, n=40) + [b"/api/v1/x.php", b"selectx", b"12",
                                       b"aXc", b"ffz", b"xxx"]
    L = max(len(d) for d in inputs)
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lengths = np.array([len(d) for d in inputs], dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d, dtype=np.uint8)
    out = scan_numpy(bank, mat, lengths)
    col = 0
    for src, n_alts in per_pattern:
        gold = re.compile(src.encode())
        got = out[:, col : col + n_alts].any(axis=1)
        for i, d in enumerate(inputs):
            assert got[i] == (gold.search(d) is not None), (
                f"bank {src!r} on {d!r}")
        col += n_alts
