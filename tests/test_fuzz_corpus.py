"""Fuzz-corpus regression replay (ISSUE 11, docs/FUZZING.md).

Every file in tools/analyze/corpus/ is a parser divergence the
differential fuzzer found (and this PR fixed) — or a deliberate pin of
a documented delta / limit behavior. `make fuzz` replays them before
mutating; this suite replays the same pins inside tier-1 so a parser
change that re-opens one fails fast, with the offending corpus file
named, even when nobody runs the fuzzer.

Python-plane pins run the listener's one-shot parse oracle
(host/httpd.py parse_request_bytes) directly; native pins drive the
real httpd binary through the fuzzer's loopback harness.
"""

import base64

import pytest

from pingoo_tpu import native_ring
from tools.analyze import fuzz


def _has_jax():
    try:
        import jax  # noqa: F401
        return True
    except Exception:
        return False


needs_jax = pytest.mark.skipif(not _has_jax(), reason="jax unavailable")
needs_native = pytest.mark.skipif(not native_ring.ensure_built(),
                                  reason="native toolchain unavailable")

CASES = fuzz.load_corpus()
# Refusal pins (reject-*/drop) never reach the rules, so they need no
# interpreter; allow/block pins do.
PY_REFUSE = [c for c in CASES if fuzz._is_refusal(c["python"])
             or c["python"] == "drop"]
PY_VERDICT = [c for c in CASES if c not in PY_REFUSE]
NATIVE = [c for c in CASES if c.get("native")]


def _ids(cases):
    return [c["_file"] for c in cases]


def test_corpus_is_committed_and_well_formed():
    assert len(CASES) >= 15, "corpus went missing — fuzzer pins gone"
    for case in CASES:
        assert case["python"] in {"reject-400", "reject-413",
                                  "reject-431", "drop", "allow",
                                  "block"}, case["_file"]
        assert base64.b64decode(case["raw_b64"]), case["_file"]
        assert case.get("desc"), case["_file"]


@pytest.mark.parametrize("case", PY_REFUSE, ids=_ids(PY_REFUSE))
def test_python_plane_refusal_pins(case):
    mutant = fuzz.corpus_mutant(case)
    # plan=None: a refusal classification must never consult the rules;
    # if the parse unexpectedly accepts, the None plan blows up — which
    # IS the regression this pin exists to catch.
    got, _ = fuzz.classify_python(mutant.raw, None)
    assert got == case["python"], \
        f"{case['_file']}: {case['desc']} (got {got})"


@needs_jax
@pytest.mark.parametrize("case", PY_VERDICT, ids=_ids(PY_VERDICT))
def test_python_plane_verdict_pins(case):
    got, _ = fuzz.classify_python(fuzz.corpus_mutant(case).raw,
                                  fuzz._fuzz_plan())
    assert got == case["python"], \
        f"{case['_file']}: {case['desc']} (got {got})"


@needs_native
@needs_jax
class TestNativePins:
    @pytest.fixture(scope="class")
    def harness(self, tmp_path_factory):
        h = fuzz.NativeHarness(
            fuzz._fuzz_plan(),
            str(tmp_path_factory.mktemp("fuzz_corpus")))
        yield h
        h.close()

    @pytest.mark.parametrize("case", NATIVE, ids=_ids(NATIVE))
    def test_native_plane_pins(self, harness, case):
        got, _ = harness.roundtrip(fuzz.corpus_mutant(case))
        assert got == case["native"], \
            f"{case['_file']}: {case['desc']} (got {got})"

    def test_full_replay_matches_make_fuzz(self, harness):
        """The exact check `make fuzz` runs first — zero regressions."""
        assert fuzz.replay_corpus(fuzz._fuzz_plan(), harness) == []


class TestLimitKnobs:
    """PINGOO_MAX_HEADER_BYTES / PINGOO_MAX_BODY_BYTES parsing: both
    planes read the same env contract (431 head / eager 413 body pins
    themselves live in the corpus above)."""

    def test_int_env_floor_and_fallback(self, monkeypatch):
        from pingoo_tpu.host.httpd import _int_env

        monkeypatch.setenv("PINGOO_T", "1024")
        assert _int_env("PINGOO_T", 99, 256) == 1024
        # Below the floor -> fall back to the default, same as the
        # native plane's "out of range; using default" path.
        monkeypatch.setenv("PINGOO_T", "12")
        assert _int_env("PINGOO_T", 99, 256) == 99
        monkeypatch.setenv("PINGOO_T", "zebra")
        assert _int_env("PINGOO_T", 99, 1) == 99
        monkeypatch.delenv("PINGOO_T")
        assert _int_env("PINGOO_T", 99, 1) == 99

    def test_defaults_match_native_plane(self):
        """The committed defaults must stay equal on both planes —
        httpd.cc kMaxReqHead/kMaxBodyBytes read the same knobs."""
        from pingoo_tpu.host import httpd

        assert httpd.MAX_HEADER_BYTES == 32 * 1024
        assert httpd.MAX_BODY_BYTES == 16 * 1024 * 1024
        import os
        import re
        src = open(os.path.join(
            os.path.dirname(httpd.__file__), "..", "native",
            "httpd.cc")).read()
        # Head cap falls back to kMaxHead; body cap to an inline 16MiB.
        assert re.search(r"kMaxHead\s*=\s*32\s*\*\s*1024", src)
        assert re.search(r"def\s*=\s*16LL\s*\*\s*1024\s*\*\s*1024", src)
        assert 'getenv("PINGOO_MAX_HEADER_BYTES")' in src
        assert 'getenv("PINGOO_MAX_BODY_BYTES")' in src
