"""Perf ledger + cross-plane timeline (ISSUE 17): compile-event
tracking, the durable CostModel cost ledger, and the span timeline's
nesting + Chrome-trace export. Pure host-side — no jax program runs
here (the live-wiring half is tools/timeline_smoke.py)."""

import json
import os

import pytest

from pingoo_tpu.obs import perf, timeline
from pingoo_tpu.obs.registry import MetricRegistry, lint_prometheus_text
from pingoo_tpu.sched.scheduler import (
    CostModel,
    load_cost_ledger,
    save_cost_ledger,
)


def _seeded_cost() -> CostModel:
    """A CostModel with every EWMA family populated by observation."""
    cost = CostModel(max_batch=256, seed_ms=4.0)
    cost.observe(16, 3.25)
    cost.observe(64, 9.5)
    cost.observe_stage("encode", 16, 0.8)
    cost.observe_stage("dispatch", 16, 0.4)
    cost.observe_stage("compute", 64, 6.0)
    cost.observe_megastep(4, 16, 2.5)   # first obs -> absorbed cold
    cost.observe_megastep(4, 16, 1.5)   # second -> steady EWMA
    cost.observe_dispatch_bytes(48 * 1024, 0.9)
    return cost


class TestCostModelPersistence:
    def test_snapshot_restore_round_trip(self):
        cost = _seeded_cost()
        snap = json.loads(json.dumps(cost.snapshot()))  # JSON round trip
        fresh = CostModel(max_batch=256)
        assert fresh.restore(snap) is True
        assert fresh.snapshot() == cost.snapshot()
        # The reloaded model estimates from the restored EWMAs (no
        # BENCH_history re-seeding): stage + megastep estimates match.
        for stage in ("encode", "dispatch", "compute"):
            assert fresh.estimate_stage(stage, 16) == pytest.approx(
                cost.estimate_stage(stage, 16))
        assert fresh.estimate_megastep(4, 16) == pytest.approx(
            cost.estimate_megastep(4, 16))
        # _mega_first (cold-compile absorption) travels too.
        assert fresh._mega_first == cost._mega_first

    def test_restore_rejects_garbage(self):
        fresh = CostModel()
        assert fresh.restore("not a dict") is False
        assert fresh.restore({}) is False
        # Unparseable keys are skipped, parseable ones restore.
        ok = fresh.restore({"ewma_ms": {"16": 2.0, "what": 1.0},
                            "stage_ewma_ms": {"bogus_stage": {"8": 1.0}},
                            "megastep_ewma_ms": {"nonsense": 3.0}})
        assert ok is True
        assert fresh._ewma == {16: 2.0}
        assert fresh._stage_ewma == {}

    def test_ledger_save_load_round_trip(self, tmp_path):
        path = str(tmp_path / "COST_LEDGER.json")
        cost = _seeded_cost()
        reg = MetricRegistry()
        assert save_cost_ledger(cost, backend="cpu", fingerprint="fp01",
                                plane="python", path=path) is True
        fresh = CostModel(max_batch=256)
        result = load_cost_ledger(fresh, backend="cpu", fingerprint="fp01",
                                  plane="python", path=path, registry=reg)
        assert result == "ok"
        assert fresh.snapshot() == cost.snapshot()
        assert reg.counter(
            "pingoo_costmodel_reload_total",
            labels={"plane": "python", "result": "ok"}).value == 1

    def test_stale_fingerprint_discarded_and_counted(self, tmp_path):
        path = str(tmp_path / "COST_LEDGER.json")
        cost = _seeded_cost()
        save_cost_ledger(cost, backend="cpu", fingerprint="fp01",
                         plane="python", path=path)
        reg = MetricRegistry()
        fresh = CostModel(max_batch=256)
        result = load_cost_ledger(fresh, backend="cpu",
                                  fingerprint="OTHER", plane="python",
                                  path=path, registry=reg)
        assert result == "stale"
        # Discarded: nothing restored from the mismatched entry.
        assert fresh._ewma == {}
        assert reg.counter(
            "pingoo_costmodel_reload_total",
            labels={"plane": "python", "result": "stale"}).value == 1
        # All four result series exist at zero-or-counted from boot.
        for res in ("ok", "stale", "missing", "error"):
            assert reg.counter(
                "pingoo_costmodel_reload_total",
                labels={"plane": "python", "result": res}) is not None

    def test_missing_and_version_mismatch(self, tmp_path):
        path = str(tmp_path / "COST_LEDGER.json")
        reg = MetricRegistry()
        fresh = CostModel()
        assert load_cost_ledger(fresh, backend="cpu", fingerprint="fp",
                                plane="python", path=path,
                                registry=reg) == "missing"
        with open(path, "w") as f:
            json.dump({"version": 999, "entries": {}}, f)
        assert load_cost_ledger(fresh, backend="cpu", fingerprint="fp",
                                plane="python", path=path,
                                registry=reg) == "stale"
        with open(path, "w") as f:
            f.write("{broken json")
        assert load_cost_ledger(fresh, backend="cpu", fingerprint="fp",
                                plane="python", path=path,
                                registry=reg) == "error"

    def test_merge_preserves_other_plane_entries(self, tmp_path):
        path = str(tmp_path / "COST_LEDGER.json")
        save_cost_ledger(_seeded_cost(), backend="cpu", fingerprint="fp",
                         plane="python", path=path)
        save_cost_ledger(_seeded_cost(), backend="cpu", fingerprint="fp",
                         plane="sidecar", path=path)
        with open(path) as f:
            doc = json.load(f)
        assert set(doc["entries"]) == {"cpu|python", "cpu|sidecar"}


class _FakeJit:
    """A jit-shaped callable with a controllable executable cache."""

    def __init__(self):
        self.cache = 0
        self.calls = 0
        self.grow_on = set()

    def __call__(self, *args):
        self.calls += 1
        if self.calls in self.grow_on:
            self.cache += 1
        return self.calls

    def _cache_size(self):
        return self.cache


class TestCompileLedger:
    def _ledger(self, tmp_path):
        return perf.CompileLedger(
            path=str(tmp_path / "PERF_LEDGER.jsonl"),
            registry=MetricRegistry())

    def test_cold_then_warm_events(self, tmp_path):
        ledger = self._ledger(tmp_path)
        fake = _FakeJit()
        fake.grow_on = {1, 3}  # compile on calls 1 (cold) and 3 (warm)
        fn = perf.instrument_jit(fake, "verdict", plane="python",
                                 fingerprint="fp", ledger=ledger)
        assert fn is not fake  # enabled -> wrapped
        for _ in range(4):
            fn()
        snap = ledger.snapshot()
        assert snap["totals"] == {"python/verdict/cold": 1,
                                  "python/verdict/warm": 1}
        kinds = [e["kind"] for e in snap["events"]]
        assert kinds == ["cold", "warm"]
        # The JSONL file agrees line-for-line with the in-memory ring.
        with open(ledger.path) as f:
            lines = [json.loads(ln) for ln in f]
        assert len(lines) == snap["compiles_total"] == 2
        assert all(ln["fingerprint"] == "fp" for ln in lines)

    def test_disabled_returns_fn_unchanged(self):
        ledger = perf.CompileLedger(path=None, registry=MetricRegistry())
        fake = _FakeJit()
        assert perf.instrument_jit(fake, "verdict", plane="python",
                                   ledger=ledger) is fake
        assert perf.instrument_jit(None, "verdict", plane="python",
                                   ledger=ledger) is None

    def test_wrapper_delegates_attributes(self, tmp_path):
        ledger = self._ledger(tmp_path)
        fake = _FakeJit()
        fn = perf.instrument_jit(fake, "lanes", plane="sidecar",
                                 ledger=ledger)
        assert fn._cache_size() == 0  # __getattr__ delegation

    def test_shape_context(self):
        bucket, k = perf._shape_context([(64, 128), (64, 16), (8, 64, 4)])
        assert bucket == 64
        assert k == 8
        assert perf._shape_context([]) == (None, None)

    def test_path_gate(self, monkeypatch):
        monkeypatch.delenv("PINGOO_PERF_LEDGER", raising=False)
        assert perf.perf_ledger_path() is None
        monkeypatch.setenv("PINGOO_PERF_LEDGER", "0")
        assert perf.perf_ledger_path() is None
        monkeypatch.setenv("PINGOO_PERF_LEDGER", "1")
        assert perf.perf_ledger_path() == perf.DEFAULT_LEDGER_FILE
        monkeypatch.setenv("PINGOO_PERF_LEDGER", "/tmp/x.jsonl")
        assert perf.perf_ledger_path() == "/tmp/x.jsonl"


class TestTimeline:
    def _timeline(self):
        return timeline.Timeline(rate=1.0, registry=MetricRegistry())

    def test_stride_sampler(self):
        tl = timeline.Timeline(rate=0.25, registry=MetricRegistry())
        hits = sum(tl.sample() for _ in range(100))
        assert hits == 25  # deterministic, no RNG
        off = timeline.Timeline(rate=0.0, registry=MetricRegistry())
        assert not any(off.sample() for _ in range(100))
        assert off.enabled is False

    def test_batch_python_spans_nest(self):
        tl = self._timeline()
        tl.batch_python(
            stages_ms={"encode_ms": 1.0, "prefilter_ms": 0.5,
                       "device_dispatch_ms": 0.5,
                       "device_compute_ms": 2.0},
            t_launch=10.0, t_resolve=10.005, t_end=10.006,
            rows=[("trace01", 9.998, 9.999)])
        spans = list(tl.spans)
        batch = [s for s in spans if s[2] == "batch"]
        assert len(batch) == 1
        b0, b1 = batch[0][3], batch[0][3] + batch[0][4]
        children = [s for s in spans
                    if s[1] == "python/batch" and s[2] != "batch"]
        assert children
        for s in children:
            assert s[3] >= b0 - 1.0
            assert s[3] + s[4] <= b1 + 1.0
        # The request lane covers enqueue -> batch end.
        req = [s for s in spans if s[2] == "request"]
        assert req and req[0][3] == pytest.approx(9.998e6)

    def test_batch_sidecar_cross_plane_join(self):
        tl = self._timeline()
        tl.batch_sidecar(t0=20.0, t1=20.001, tpf=20.0015, t2=20.002,
                         t_sync=20.004, t_resolve=20.004, t_end=20.005,
                         rows=[("t-7", 19990.0)])  # enq_ms = 19.99 s
        spans = list(tl.spans)
        join = [s for s in spans if s[0] == "native"
                and s[2] == "ring_wait"]
        assert len(join) == 1
        # enq at 19.99 s, sidecar pickup at 20.0 s -> 10 ms wait.
        assert join[0][4] == pytest.approx(10_000.0)

    def test_batch_sidecar_megastep_slice_fallback(self):
        tl = self._timeline()
        # No per-slice dispatch points (t0=0): the batch span must
        # cover the resolve window, not start at monotonic zero.
        tl.batch_sidecar(t0=0.0, t1=0.0, tpf=0.0, t2=0.0, t_sync=0.0,
                         t_resolve=30.0, t_end=30.002)
        batch = [s for s in tl.spans if s[2] == "batch"][0]
        assert batch[3] == pytest.approx(30.0e6)

    def test_chrome_trace_export(self):
        tl = self._timeline()
        tl.batch_python(stages_ms={"encode_ms": 1.0}, t_launch=1.0,
                        t_resolve=1.002, t_end=1.003)
        doc = json.loads(tl.chrome_trace_json())
        assert doc["clock"]["unit"] == "monotonic_us"
        names = {e["name"] for e in doc["traceEvents"]
                 if e["ph"] == "M"}
        assert "process_name" in names and "thread_name" in names
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert xs and all(e["dur"] >= 0 for e in xs)
        assert doc["otherData"]["spans"] == len(tl.spans)

    def test_bounded_retention(self):
        tl = self._timeline()
        for i in range(tl.spans.maxlen + 100):
            tl.add_span("python", "t", "s", float(i), 1.0)
        assert len(tl.spans) == tl.spans.maxlen

    def test_sample_rate_env(self, monkeypatch):
        monkeypatch.delenv("PINGOO_TIMELINE_SAMPLE", raising=False)
        assert timeline.timeline_sample_rate() == 0.0
        monkeypatch.setenv("PINGOO_TIMELINE_SAMPLE", "0.1")
        assert timeline.timeline_sample_rate() == pytest.approx(0.1)
        monkeypatch.setenv("PINGOO_TIMELINE_SAMPLE", "7")
        assert timeline.timeline_sample_rate() == 1.0
        monkeypatch.setenv("PINGOO_TIMELINE_SAMPLE", "junk")
        assert timeline.timeline_sample_rate() == 0.0


class TestExposition:
    def test_perf_series_lint_clean(self):
        reg = MetricRegistry()
        ledger = perf.CompileLedger(path=None, registry=reg)
        ledger.ensure_instruments("python")
        ledger.ensure_instruments("sidecar")
        tl = timeline.Timeline(rate=0.0, registry=reg)
        tl.ensure_instruments("python")
        tl.ensure_instruments("sidecar")
        for res in ("ok", "stale", "missing", "error"):
            load_cost_ledger(CostModel(), backend="cpu", fingerprint="",
                             plane="python", path=os.devnull,
                             registry=reg)
            break  # one call creates all four series eagerly
        text = reg.prometheus_text()
        assert lint_prometheus_text(text) == []
        for name in ("pingoo_compile_total", "pingoo_compile_ms",
                     "pingoo_timeline_spans_total",
                     "pingoo_costmodel_reload_total"):
            assert name in text


class TestBenchRegressRefusal:
    def _run(self, tmp_path, entries):
        import tools.bench_regress as br

        path = str(tmp_path / "hist.jsonl")
        with open(path, "w") as f:
            for e in entries:
                f.write(json.dumps(e) + "\n")
        return br.main(["--file", path])

    def test_cross_backend_refused(self, tmp_path, capsys):
        rc = self._run(tmp_path, [
            {"ts": 1, "backend": "device", "value": 100},
            {"ts": 2, "backend": "cpu-diagnostic", "value": 5},
        ])
        assert rc == 0
        out = capsys.readouterr().out
        assert "REFUSED" in out
        assert "cpu-diagnostic" in out and "device" in out

    def test_unstamped_latest_is_an_error(self, tmp_path, capsys):
        rc = self._run(tmp_path, [
            {"ts": 1, "backend": "device", "value": 100},
            {"ts": 2, "value": 90},
        ])
        assert rc == 2
        assert "no 'backend' stamp" in capsys.readouterr().err

    def test_same_backend_still_compares(self, tmp_path, capsys):
        rc = self._run(tmp_path, [
            {"ts": 1, "backend": "device", "value": 100},
            {"ts": 2, "backend": "device", "value": 101},
        ])
        assert rc == 0
        assert "bench-regress: OK" in capsys.readouterr().out
