"""Streaming body-inspection tests (ISSUE 13).

The core property is split-anywhere parity: a payload split at EVERY
byte boundary (and across ring-window boundaries) must produce verdict
bits identical to the contiguous scan and to the `re` interpreter
oracle, across NFA / DFA / prefilter-lazy modes and odd batch tails —
WAFFLED's split-payload discrepancy class, pinned as a test. Also
covers the chunk-carry kernel primitives directly (dfa_scan_chunk /
prefilter_scan_chunk vs their whole-field scans), lane composition
(merge_actions), flow-table admission/eviction degrades, and the
PINGOO_BODY_INSPECT=off bit-exactness gate.
"""

import os
import random

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pingoo_tpu.compiler import repat  # noqa: E402
from pingoo_tpu.compiler.nfa import build_bank, lower_bank_to_dfa  # noqa: E402
from pingoo_tpu.engine import bodyscan  # noqa: E402
from pingoo_tpu.engine.bodyscan import (  # noqa: E402
    BodyRule,
    BodyScanner,
    BodyWindow,
    body_lanes_oracle,
    compile_body_plan,
    merge_actions,
    split_payload,
)
from pingoo_tpu.ops.bitsplit_dfa import (  # noqa: E402
    dfa_finalize,
    dfa_init_state,
    dfa_scan,
    dfa_scan_chunk,
    dfa_to_tables,
)
from pingoo_tpu.ops.nfa_scan import (  # noqa: E402
    bank_to_tables,
    extract_slots,
    init_scan_state,
    nfa_scan,
    scan_chunk,
)
from pingoo_tpu.ops.prefilter import (  # noqa: E402
    bank_to_prefilter_tables,
    build_prefilter_bank,
    prefilter_extract,
    prefilter_init_state,
    prefilter_scan,
    prefilter_scan_chunk,
)

RULES = bodyscan.DEFAULT_BODY_RULES

PAYLOADS = [
    b"",
    b"a",
    b"hello world, nothing to see",
    b"id=1+UNION SELECT password from users--",
    b"union selec",  # near miss
    b"x" * 37 + b"<ScRiPt>alert(1)</script>" + b"y" * 11,
    b"../../" + b"../../etc/shadow",
    b"path=....//....//etc/passwd\x00",
    b"e" * 64 + b"eval(base64_decode('aGk='))",  # captcha rule
    b"union" + b" " * 30 + b"select",  # no match: literal needs one space
    b"UNION SELECT",  # exact boundary match at both ends
    b"<scrip" + b"t src=x>",  # literal straddle bait
    b"' or '1'='1",
    bytes(random.Random(7).randrange(256) for _ in range(301)),
]


def _split_points(n: int):
    """Every byte boundary for short payloads, a dense sample for long."""
    if n <= 64:
        return range(n + 1)
    pts = set(range(0, 17))
    pts |= {n - i for i in range(17) if n - i >= 0}
    pts |= set(random.Random(n).sample(range(n + 1), 24))
    return sorted(pts)


def _feed(scanner, payload, cuts, flow_id=1):
    """Drive a payload through the scanner split at `cuts` offsets."""
    bounds = [0] + list(cuts) + [len(payload)]
    outs = []
    seq = 0
    # slice into (possibly empty) windows between consecutive bounds
    pieces = [payload[a:b] for a, b in zip(bounds, bounds[1:])]
    if not pieces:
        pieces = [b""]
    for i, piece in enumerate(pieces):
        outs = scanner.scan_windows([BodyWindow(
            flow_id=flow_id, win_seq=seq, data=piece,
            final=(i == len(pieces) - 1))])
        seq += 1
    assert outs, "final window must yield a verdict"
    return outs[0]


@pytest.fixture(scope="module")
def plan():
    return compile_body_plan(RULES, window=64)


def test_plan_shape(plan):
    assert plan.slot_rule.shape[0] >= len(RULES)
    assert plan.dfa_tables is not None and plan.dfa_tables.exact
    assert plan.pf_tables is not None
    assert plan.lazy_ok, "seed literal rules must enable the lazy cascade"


# -- kernel chunk-carry primitives -------------------------------------------


def test_dfa_chunk_matches_whole_scan(plan):
    rng = random.Random(3)
    tables = plan.dfa_tables
    B, L = 5, 96
    data = np.zeros((B, L), dtype=np.uint8)
    rows = [b"union select now", b"<script>x", b"no match here at all",
            b"", b"ev" + b"al(" + bytes(rng.randrange(256)
                                        for _ in range(40))]
    lens = np.array([len(r) for r in rows], dtype=np.int32)
    for i, r in enumerate(rows):
        data[i, :len(r)] = np.frombuffer(r, np.uint8)
    whole = np.asarray(dfa_scan(tables, jnp.asarray(data),
                                jnp.asarray(lens)))
    for cut in (0, 1, 7, 48, 95, 96):
        st, H = dfa_init_state(B, tables.num_words)
        st, H = dfa_scan_chunk(tables, jnp.asarray(data[:, :cut]),
                               jnp.asarray(lens), st, H, 0)
        st, H = dfa_scan_chunk(tables, jnp.asarray(data[:, cut:]),
                               jnp.asarray(lens), st, H, cut)
        got = np.asarray(dfa_finalize(tables, st, H, jnp.asarray(lens)))
        np.testing.assert_array_equal(got, whole)


def test_prefilter_chunk_matches_whole_scan(plan):
    tables = plan.pf_tables
    B, L = 4, 80
    rows = [b"xxunion selectyy", b"union sele", b"ct from t",
            b"eval(') /etc/passwd"]
    data = np.zeros((B, L), dtype=np.uint8)
    lens = np.array([len(r) for r in rows], dtype=np.int32)
    for i, r in enumerate(rows):
        data[i, :len(r)] = np.frombuffer(r, np.uint8)
    whole = np.asarray(prefilter_scan(tables, jnp.asarray(data),
                                      jnp.asarray(lens)))
    for cut in (0, 3, 9, 40, 80):
        S, H = prefilter_init_state(B, tables.init.shape[0])
        S, H = prefilter_scan_chunk(tables, jnp.asarray(data[:, :cut]),
                                    jnp.asarray(lens), S, H, 0)
        S, H = prefilter_scan_chunk(tables, jnp.asarray(data[:, cut:]),
                                    jnp.asarray(lens), S, H, cut)
        got = np.asarray(prefilter_extract(tables, H))
        np.testing.assert_array_equal(got, whole)


def test_prefilter_literal_straddle(plan):
    """A factor split across the chunk boundary completes on the S
    carry — the straddle case the overlap-tail-free design rests on."""
    tables = plan.pf_tables
    payload = b"zzzunion selectzzz"
    mid = payload.index(b"n sel")  # cut inside the literal
    data = np.frombuffer(payload, np.uint8)[None, :]
    lens = np.array([len(payload)], dtype=np.int32)
    whole = np.asarray(prefilter_scan(tables, jnp.asarray(data),
                                      jnp.asarray(lens)))
    S, H = prefilter_init_state(1, tables.init.shape[0])
    S, H = prefilter_scan_chunk(tables, jnp.asarray(data[:, :mid]),
                                jnp.asarray(lens), S, H, 0)
    S, H = prefilter_scan_chunk(tables, jnp.asarray(data[:, mid:]),
                                jnp.asarray(lens), S, H, mid)
    np.testing.assert_array_equal(
        np.asarray(prefilter_extract(tables, H)), whole)
    assert whole.any(), "the union-select factor must be present"


# -- split-anywhere property --------------------------------------------------


def _contiguous_lanes(plan, payload, mode):
    scanner = BodyScanner(plan, mode=mode)
    v = scanner.scan_buffered(payload)
    return v.unverified, v.verified_block, v.matched


@pytest.mark.parametrize("mode", ["nfa", "dfa"])
def test_split_anywhere_parity(plan, mode):
    for payload in PAYLOADS:
        oracle = body_lanes_oracle(plan, payload)
        contiguous = _contiguous_lanes(plan, payload, mode)
        assert contiguous[:2] == oracle[:2], (payload, mode)
        assert set(contiguous[2]) == set(oracle[2]), (payload, mode)
        for cut in _split_points(len(payload)):
            scanner = BodyScanner(plan, mode=mode)
            got = _feed(scanner, payload, [cut])
            assert (got.unverified, got.verified_block) == oracle[:2], (
                payload, mode, cut)
            assert set(got.matched) == set(oracle[2]), (payload, mode, cut)


@pytest.mark.parametrize("lazy", ["auto", "off"])
def test_split_anywhere_lazy_modes(plan, lazy, monkeypatch):
    monkeypatch.setenv("PINGOO_BODY_LAZY", lazy)
    for payload in PAYLOADS:
        oracle = body_lanes_oracle(plan, payload)
        for cut in _split_points(len(payload))[::3]:
            scanner = BodyScanner(plan, mode="nfa")
            assert scanner.lazy == (lazy == "auto")
            got = _feed(scanner, payload, [cut])
            assert (got.unverified, got.verified_block) == oracle[:2], (
                payload, lazy, cut)


def test_multiwindow_three_way_splits(plan):
    """Windows smaller than the ring cap: three-way and many-way splits,
    batched across interleaved flows (odd batch tails)."""
    rng = random.Random(11)
    payloads = [p for p in PAYLOADS if p]
    oracles = {i: body_lanes_oracle(plan, p) for i, p in
               enumerate(payloads)}
    for mode in ("nfa", "dfa"):
        scanner = BodyScanner(plan, mode=mode)
        # interleave windows of all flows in one scan_windows call
        windows = []
        for i, p in enumerate(payloads):
            cuts = sorted(rng.sample(range(len(p) + 1),
                                     min(3, len(p))))
            bounds = [0] + cuts + [len(p)]
            pieces = [p[a:b] for a, b in zip(bounds, bounds[1:])]
            for j, piece in enumerate(pieces):
                windows.append(BodyWindow(i, j, piece,
                                          final=(j == len(pieces) - 1)))
        verdicts = scanner.scan_windows(windows)
        assert len(verdicts) == len(payloads)
        for v in verdicts:
            assert (v.unverified, v.verified_block) == oracles[v.flow_id][:2]


def test_regex_rules_split_parity():
    """Regex body rules (rep loops, classes) through the same property;
    unbounded footprint disables lazy but carry must stay exact."""
    rules = (
        BodyRule("rx-sel-from", r"select[ ]+[a-z*]+[ ]+from", "regex", True,
                 ("block",)),
        BodyRule("rx-digits", r"id=[0-9]+--", "regex", False, ("captcha",)),
    )
    plan = compile_body_plan(rules, window=32)
    payloads = [
        b"SELECT * FROM users",
        b"x" * 30 + b"select  password   from creds" + b"y" * 9,
        b"id=12345--",
        b"id=--",
        b"select from",
    ]
    for mode in ["nfa"] + (["dfa"] if plan.dfa_tables is not None else []):
        for p in payloads:
            oracle = body_lanes_oracle(plan, p)
            for cut in _split_points(len(p)):
                scanner = BodyScanner(plan, mode=mode)
                got = _feed(scanner, p, [cut])
                assert (got.unverified, got.verified_block) == oracle[:2], (
                    p, mode, cut)


def test_ring_window_sized_splits(plan):
    """Payloads longer than the scan window arrive as multiple ring
    windows regardless of transport chunking — exercise window-cap
    slicing plus an extra transport split."""
    p = (b"A" * 100 + b"union sel" + b"B" * 60 + b"ect nope"
         + b"C" * 50 + b"UNION SELECT" + b"D" * 40)
    oracle = body_lanes_oracle(plan, p)
    for mode in ("nfa", "dfa"):
        for w in (16, 64, 4096):
            scanner = BodyScanner(plan, mode=mode)
            pieces = split_payload(p, w)
            outs = []
            for i, piece in enumerate(pieces):
                outs = scanner.scan_windows([BodyWindow(
                    9, i, piece, final=(i == len(pieces) - 1))])
            got = outs[0]
            assert (got.unverified, got.verified_block) == oracle[:2], (
                mode, w)


# -- lanes + composition ------------------------------------------------------


def test_lane_semantics(plan):
    # captcha rule only
    v = BodyScanner(plan).scan_buffered(b"eval('x')")
    assert v.unverified == bodyscan.ACTION_CAPTCHA
    assert not v.verified_block
    # block rule wins the first-action race when it comes first
    v = BodyScanner(plan).scan_buffered(b"<script>eval('x')")
    assert v.unverified == bodyscan.ACTION_BLOCK
    assert v.verified_block


def test_merge_actions():
    CAPTCHA, BLOCK, VB = 2, 1, 0x4
    route = 0x5 << 3
    # metadata first-action wins
    assert merge_actions(route | CAPTCHA, BLOCK, True) == (
        route | VB | CAPTCHA)
    # body supplies the action when metadata had none
    assert merge_actions(route, CAPTCHA, False) == route | CAPTCHA
    assert merge_actions(0, BLOCK, True) == VB | BLOCK
    # verified-block ORs across both verdicts
    assert merge_actions(VB, 0, False) == VB
    assert merge_actions(0, 0, True) == VB
    # no body match leaves the metadata byte untouched
    for meta in (0, BLOCK, CAPTCHA, VB | BLOCK, route | CAPTCHA):
        assert merge_actions(meta, 0, False) == meta


def test_merge_actions_matches_native_twin():
    # httpd.cc merge_body_action is the C twin of merge_actions; pin
    # them byte-for-byte over the whole domain (meta byte x body
    # verdict byte, where the body byte is BodyVerdict.action_byte():
    # unverified in bits 0-1, verified-block in bit 2).
    def c_twin(meta, body):
        unverified = (meta & 3) if (meta & 3) else (body & 3)
        return (meta & 0xF8) | ((meta | body) & 4) | unverified

    for meta in range(256):
        for unverified in range(4):
            for verified in (False, True):
                body = unverified | (0x4 if verified else 0)
                assert merge_actions(meta, unverified, verified) == \
                    c_twin(meta, body), (meta, unverified, verified)


# -- flow table ---------------------------------------------------------------


def test_flow_eviction_degrades(plan):
    scanner = BodyScanner(plan, mode="nfa", max_flows=2)
    scanner.scan_windows([BodyWindow(1, 0, b"union sel"),
                          BodyWindow(2, 0, b"<scr")])
    assert scanner.flows_active == 2
    # third flow evicts the stalest; evicted flow finishes degraded
    scanner.scan_windows([BodyWindow(3, 0, b"x")])
    assert scanner.flows_active == 2
    assert scanner.stats.degrade_total == 1
    out = scanner.scan_windows([BodyWindow(1, 1, b"ect", final=True)])
    assert out and out[0].degraded and out[0].unverified == 0


def test_flow_ttl_eviction(plan):
    clock = [0]
    scanner = BodyScanner(plan, mode="nfa", flow_ttl_ms=100,
                          now_ms=lambda: clock[0])
    scanner.scan_windows([BodyWindow(5, 0, b"union")])
    clock[0] = 500
    assert scanner.evict_stale() == 1
    assert scanner.flows_active == 0
    assert scanner.stats.degrade_total == 1


def test_window_gap_degrades(plan):
    scanner = BodyScanner(plan, mode="nfa")
    scanner.scan_windows([BodyWindow(7, 0, b"union select")])
    out = scanner.scan_windows([BodyWindow(7, 2, b"x", final=True)])
    assert out[0].degraded


def test_lazy_skips_clean_traffic(plan):
    """Bodies with no factor hit must never run the NFA at all."""
    scanner = BodyScanner(plan, mode="nfa")
    assert scanner.lazy
    v = scanner.scan_buffered(b"perfectly ordinary form data " * 20)
    assert v.unverified == 0 and not v.verified_block
    assert scanner.stats.lazy_skips > 0


# -- stats / gate -------------------------------------------------------------


def test_stats_accumulate(plan):
    scanner = BodyScanner(plan, mode="nfa")
    scanner.scan_buffered(b"union select " * 40)
    st = scanner.stats
    assert st.windows_total >= 1
    assert st.bytes_total == len(b"union select " * 40)
    assert st.flows_started == st.flows_finished == 1
    assert st.carry_depth >= 1


def test_inspect_gate_default_off(monkeypatch):
    monkeypatch.delenv("PINGOO_BODY_INSPECT", raising=False)
    assert not bodyscan.body_inspect_enabled()
    monkeypatch.setenv("PINGOO_BODY_INSPECT", "on")
    assert bodyscan.body_inspect_enabled()


def test_custom_rules_file(tmp_path, monkeypatch):
    import json

    path = tmp_path / "body_rules.json"
    path.write_text(json.dumps([
        {"name": "r1", "pattern": "abc", "kind": "literal",
         "actions": ["block"]},
    ]))
    monkeypatch.setenv("PINGOO_BODY_RULES", str(path))
    rules = bodyscan.load_body_rules()
    assert rules == (BodyRule("r1", "abc", "literal", False, ("block",)),)
