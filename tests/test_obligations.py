"""Lowering-soundness obligations (ISSUE 18, docs/STATIC_ANALYSIS.md
"Prove"): the compile-time proof layer, its proof-block serialization,
the ring-protocol model checker, and the compile-surface membership
check. The full seed-corpus discharge + mutation battery lives in
`make prove` (tools/analyze/prove.py); these are the unit-level twins.
"""

import ast
import copy
import dataclasses
import json
import os

import pytest

from pingoo_tpu.compiler import obligations as ob
from pingoo_tpu.compiler import repat
from pingoo_tpu.compiler.plan import compile_ruleset
from pingoo_tpu.utils.crs import generate_ruleset


@pytest.fixture(scope="module")
def plan():
    rules, lists = generate_ruleset(80, with_lists=True,
                                    list_sizes=(64, 16))
    return compile_ruleset(rules, lists)


# -- pillar 1: plan proofs ---------------------------------------------------


def test_seed_plan_discharges(plan):
    proof = ob.prove_plan(plan, fingerprint="fp80")
    assert proof.ok, [o.to_dict() for o in proof.failures()]
    counts = proof.counts()
    assert counts["proved"] > 0 and counts["failed"] == 0
    assert proof.fingerprint == "fp80"


def test_body_plan_discharges():
    from pingoo_tpu.engine.bodyscan import compile_body_plan

    proof = ob.prove_body_plan(compile_body_plan())
    assert proof.ok, [o.to_dict() for o in proof.failures()]
    names = {o.name for o in proof.obligations}
    assert "body-carry-closure" in names and "body-tables" in names


def test_narrowed_staging_cap_refused(plan):
    m = copy.copy(plan)
    m.staging_caps = dict(plan.staging_caps)
    f = next(iter(plan.field_specs))
    m.staging_caps[f] = int(plan.field_specs[f]) + 1  # past the spec
    failed = [o for o in ob.check_staging(m) if o.status == "failed"]
    assert failed and f in failed[0].detail


def test_weakened_prefilter_factor_refused(plan):
    pf = plan.prefilter
    if not any(any(c >= 0 for c in cs) and "@" not in k
               for k, cs in pf.slot_codes.items()):
        pytest.skip("no factor-gated slot in the small seed plan")
    from tools.analyze.prove import _mutation_weakened_factor

    assert _mutation_weakened_factor(plan, ob)


def test_certify_extension_accepts_real_rewrite_and_rejects_tamper():
    orig = repat.compile_regex("ab*c")[0]
    assert repat.has_unbounded_rep(orig)
    ext = repat.extend_footprint(orig, 8)
    assert ext is not None
    assert ob.certify_extension(orig, ext, 8) is None
    # Dropping one justified optional is no longer the certified rewrite.
    tampered = dataclasses.replace(ext, positions=ext.positions[:-1])
    assert ob.certify_extension(orig, tampered, 8) is not None
    # Neither is flipping an anchor flag.
    flipped = dataclasses.replace(ext, anchor_start=not ext.anchor_start)
    assert ob.certify_extension(orig, flipped, 8) is not None


# -- proof-block serialization (the cache contract) --------------------------


def _proof(status="proved"):
    return ob.PlanProof(fingerprint="fp", obligations=[
        ob.Obligation("staging-caps", "caps", status, "detail")])


def test_proof_block_round_trip():
    proof = _proof()
    block = proof.to_dict()
    assert ob.proof_block_valid(block, "fp")
    assert ob.proof_block_valid(block, "")  # empty fp = unpinned
    back = ob.PlanProof.from_dict(block)
    assert back.to_dict() == block  # digest is reproducible


def test_proof_block_rejects_tampering():
    block = _proof().to_dict()
    bad = dict(block, digest="0" * 64)
    assert not ob.proof_block_valid(bad, "fp")
    renamed = json.loads(json.dumps(block))
    renamed["obligations"][0]["name"] = "tampered"
    assert not ob.proof_block_valid(renamed, "fp")
    assert not ob.proof_block_valid(dict(block, format=0), "fp")
    assert not ob.proof_block_valid(block, "other-fingerprint")
    assert not ob.proof_block_valid(_proof("failed").to_dict(), "fp")
    assert not ob.proof_block_valid("not a dict", "fp")


def test_require_raises_with_failure_names():
    ob.require(_proof())  # ok proof passes through
    with pytest.raises(ob.ObligationError) as ei:
        ob.require(_proof("failed"))
    assert "staging-caps" in str(ei.value)
    assert ei.value.proof.counts()["failed"] == 1


# -- pillar 3: ring-protocol model checker -----------------------------------


def test_ring_and_body_models_hold():
    from tools.analyze import ringcheck

    assert ringcheck.run(quiet=True) == 0


def test_ring_model_mutations_caught(capsys):
    from tools.analyze import ringcheck

    assert ringcheck.run(mutate="floor_before_post", quiet=True) != 0
    assert ringcheck.run(mutate="silent_gap", quiet=True) != 0
    out = capsys.readouterr().out
    assert "FAIL" in out  # the witness trace prints even when quiet


# -- pillar 2: compile surface ----------------------------------------------


def _event(**kw):
    base = {"plane": "python", "fn": "verdict", "kind": "cold"}
    base.update(kw)
    return base


def test_event_in_surface_membership():
    from pingoo_tpu.obs.perf import event_in_surface

    surf = {"planes": ["python", "sidecar"], "fns": ["verdict", "score"],
            "kinds": ["cold", "warm"], "batch_buckets": [8, 16],
            "k_rungs": [1, 2, 4]}
    assert event_in_surface(_event(), surf) is None
    assert event_in_surface(_event(batch_bucket=16, k=2), surf) is None
    assert "fn=" in event_in_surface(_event(fn="mystery"), surf)
    assert "plane=" in event_in_surface(_event(plane="gpu"), surf)
    assert "kind=" in event_in_surface(_event(kind="hot"), surf)
    assert event_in_surface(_event(batch_bucket=26), surf) \
        == "batch_bucket=26"
    assert event_in_surface(_event(k=3), surf) == "k=3"
    # Widths gate only when the surface carries a widths key.
    assert event_in_surface(_event(widths=[[4, 8]]), surf) is None
    surf["widths"] = [[[4, 8]]]
    assert event_in_surface(_event(widths=[[4, 8]]), surf) is None
    assert event_in_surface(_event(widths=[[4, 99]]), surf) == "widths"


def test_unregistered_factory_fails_the_surface_walk():
    from tools.analyze import surface as surface_mod

    entries, problems = [], []
    tree = ast.parse("def make_bogus_fn(plan):\n    return None\n")
    surface_mod._scan_module(tree, "pingoo_tpu/engine/fake.py",
                             entries, problems)
    assert problems and "make_bogus_fn" in problems[0]


def test_unknown_instrument_label_fails_the_surface_walk():
    from tools.analyze import surface as surface_mod

    entries, problems = [], []
    tree = ast.parse("f = instrument_jit(g, 'mystery', plane='python')")
    surface_mod._scan_module(tree, "pingoo_tpu/engine/fake.py",
                             entries, problems)
    assert problems and "mystery" in problems[0]


def test_committed_surface_matches_the_tree():
    """COMPILE_SURFACE.json is generated (make prove / make surface);
    drift between the committed artifact and a fresh walk means someone
    added a jit entry point without regenerating it."""
    from tools.analyze import surface as surface_mod

    with open(surface_mod.DEFAULT_PATH, encoding="utf-8") as f:
        committed = json.load(f)
    assert committed == surface_mod.build_surface()
