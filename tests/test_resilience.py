"""Sidecar supervision (ISSUE 10, docs/RESILIENCE.md): liveness
protocol primitives, crash-reattach reconciliation, degradation
ladder, chaos injector, and the stop()/SIGTERM drain contract.

The subprocess end of these scenarios — a real SIGKILLed consumer,
bounded p99 across the outage — lives in tools/chaos_smoke.py
(`make chaos-smoke`); here the same protocol is driven in-process so
tier-1 stays fast and deterministic. A "dead epoch" is simulated by
dequeuing tickets from a ring without ever posting their verdicts:
exactly the shm state a SIGKILL between dequeue and post leaves
behind, minus the process teardown.
"""

import threading
import time

import numpy as np
import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.native_ring import Ring, RingSidecar

pytestmark = pytest.mark.skipif(
    not native_ring.ensure_built(), reason="native toolchain unavailable")


def _has_jax():
    try:
        import jax  # noqa: F401
        return True
    except Exception:
        return False


needs_jax = pytest.mark.skipif(not _has_jax(), reason="jax unavailable")


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    """Supervision knobs the sidecar reads at construction time; a
    leaked PINGOO_CHAOS would fault-inject every test below."""
    for var in ("PINGOO_CHAOS", "PINGOO_DFA", "PINGOO_MESH",
                "PINGOO_SCHED_MODE", "PINGOO_PARITY_SAMPLE",
                "PINGOO_PIPELINE", "PINGOO_PIPELINE_DEPTH"):
        monkeypatch.delenv(var, raising=False)


def _make_plan():
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    rules = [
        RuleConfig(name="waf", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.path.starts_with("/evil")')),
        RuleConfig(name="bot", actions=(Action.BLOCK,),
                   expression=compile_expression(
                       'http_request.user_agent.contains("chaosbot")')),
    ]
    return compile_ruleset(rules, {})


@pytest.fixture(scope="module")
def plan():
    return _make_plan()


def _enq(ring, i):
    path = b"/evil/%d" % i if i % 3 == 0 else b"/ok/%d" % i
    ua = b"chaosbot/1.0" if i % 7 == 0 else b"Mozilla/5.0"
    return ring.enqueue(method=b"GET", host=b"r.test", path=path,
                        url=path, user_agent=ua)


def _want(i):
    return 1 if (i % 3 == 0 or i % 7 == 0) else 0


def _poll_all(ring, need, timeout=120.0):
    """ticket -> [actions] until `need` verdicts arrive, plus a short
    grace window so a double-post would be caught, not raced past."""
    got: dict = {}
    count = 0
    deadline = time.monotonic() + timeout
    while count < need and time.monotonic() < deadline:
        v = ring.poll_verdict()
        if v is None:
            time.sleep(0.002)
            continue
        t, a, _ = v
        got.setdefault(t, []).append(a)
        count += 1
    grace = time.monotonic() + 0.2
    while time.monotonic() < grace:
        v = ring.poll_verdict()
        if v is None:
            time.sleep(0.01)
            continue
        t, a, _ = v
        got.setdefault(t, []).append(a)
    return got


class TestLivenessProtocol:
    """Ring v5 header primitives — pure shm, no verdict engine."""

    def test_attach_bumps_epoch_and_stamps_heartbeat(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            lv = ring.liveness()
            # heartbeat_ms == 0 is the bootstrap sentinel the native
            # detector keys on: no sidecar has EVER attached, so the
            # data plane must not flip degraded (httpd.cc).
            assert lv["epoch"] == 0 and lv["heartbeat_ms"] == 0
            assert ring.sidecar_attach() == 1
            lv = ring.liveness()
            assert lv["epoch"] == 1
            assert 0 < lv["heartbeat_ms"] <= lv["now_ms"]
            # One consumer generation = one epoch.
            assert ring.sidecar_attach() == 2
        finally:
            ring.close()

    def test_heartbeat_advances_on_ring_clock(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            ring.sidecar_attach()
            hb0 = ring.liveness()["heartbeat_ms"]
            time.sleep(0.02)
            ring.heartbeat()
            lv = ring.liveness()
            assert lv["heartbeat_ms"] > hb0
            assert lv["heartbeat_ms"] <= lv["now_ms"]
        finally:
            ring.close()

    def test_posted_floor_is_monotonic_max(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            ring.set_posted_floor(5)
            assert ring.liveness()["posted_floor"] == 5
            ring.set_posted_floor(3)  # stale writer loses the CAS race
            assert ring.liveness()["posted_floor"] == 5
            ring.set_posted_floor(9)
            assert ring.liveness()["posted_floor"] == 9
        finally:
            ring.close()

    def test_reclaim_consumed_slot_returns_intact_bytes(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            _enq(ring, 0)
            _enq(ring, 1)
            assert len(ring.dequeue_batch()) == 2  # consumed, unposted
            s = ring.reclaim(0)
            assert s is not None
            assert bytes(s[0]["path"][:int(s[0]["path_len"])]) == b"/evil/0"
            s = ring.reclaim(1)
            assert s is not None
            assert bytes(s[0]["path"][:int(s[0]["path_len"])]) == b"/ok/1"
        finally:
            ring.close()

    def test_reclaim_recycled_slot_returns_none(self, tmp_path):
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        try:
            for i in range(8):
                assert _enq(ring, i) is not None
            assert len(ring.dequeue_batch()) == 8
            for i in range(8, 16):  # wrap: every slot overwritten
                assert _enq(ring, i) is not None
            for ticket in range(8):
                assert ring.reclaim(ticket) is None  # -> fail-open
            # ... and the reclaim probes did not disturb the live
            # generation occupying those slots.
            slots = ring.dequeue_batch()
            assert slots["ticket"].tolist() == list(range(8, 16))
        finally:
            ring.close()


@needs_jax
class TestReattachReconciliation:
    def test_orphans_reevaluated_exactly_once(self, tmp_path, plan,
                                              monkeypatch):
        monkeypatch.setenv("PINGOO_PARITY_SAMPLE", "1")
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = None
        try:
            ring.sidecar_attach()  # epoch 1: the consumer that "dies"
            n = 24
            for i in range(n):
                assert _enq(ring, i) is not None
            # Crash window: dequeued, never posted, floor never moved.
            assert len(ring.dequeue_batch(10)) == 10
            lv = ring.liveness()
            assert lv["req_tail"] == 10 and lv["posted_floor"] == 0

            sidecar = RingSidecar(ring, plan, {}, max_batch=16)
            assert sidecar.epoch == 2
            # All 10 orphan slots survived intact -> re-evaluated, not
            # failed open; floor advanced so a THIRD attach rescans
            # nothing.
            assert sidecar.reconciled == {"reeval": 10, "failopen": 0}
            assert ring.liveness()["posted_floor"] == 10
            assert sidecar.stats()["supervision"] == {
                "epoch": 2, "reconciled": {"reeval": 10, "failopen": 0}}

            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": n - 10},
                                 daemon=True)
            t.start()
            got = _poll_all(ring, n)
            t.join(60)
            assert not t.is_alive()
            assert sorted(got) == list(range(n))           # zero lost
            assert all(len(a) == 1 for a in got.values())  # exactly once
            for i in range(n):  # reconciled verdicts bit-exact too
                assert got[i][0] & 3 == _want(i), i
            assert sidecar.parity is not None
            assert sidecar.parity.flush(30)
            assert sidecar.parity.mismatch_total.value == 0
        finally:
            if sidecar is not None:
                sidecar.stop()
            ring.close()

    def test_recycled_orphans_fail_open(self, tmp_path, plan):
        ring = Ring(str(tmp_path / "ring"), capacity=8, create=True)
        sidecar = None
        try:
            ring.sidecar_attach()
            for i in range(8):
                assert _enq(ring, i) is not None
            assert len(ring.dequeue_batch()) == 8  # dead epoch's batch
            for i in range(8, 16):  # producers lapped the dead consumer
                assert _enq(ring, i) is not None

            sidecar = RingSidecar(ring, plan, {}, max_batch=16)
            assert sidecar.reconciled == {"reeval": 0, "failopen": 8}
            # Fail-open is ALLOW even for tickets whose (overwritten)
            # request would have matched a block rule.
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": 8}, daemon=True)
            t.start()
            got = _poll_all(ring, 16)
            t.join(60)
            assert not t.is_alive()
            assert sorted(got) == list(range(16))
            assert all(len(a) == 1 for a in got.values())
            for ticket in range(8):
                assert got[ticket][0] & 3 == 0, ticket
            for i in range(8, 16):  # the live generation: full verdicts
                assert got[i][0] & 3 == _want(i), i
        finally:
            if sidecar is not None:
                sidecar.stop()
            ring.close()


@needs_jax
class TestHeartbeatFreezeDetection:
    def test_frozen_heartbeat_goes_stale_while_serving(self, tmp_path,
                                                       plan, monkeypatch):
        monkeypatch.setenv("PINGOO_CHAOS", "heartbeat_freeze")
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=16)
        monkeypatch.delenv("PINGOO_CHAOS")
        try:
            assert sidecar.chaos.freeze_heartbeat
            hb0 = ring.liveness()["heartbeat_ms"]  # the attach stamp
            assert hb0 > 0
            for i in range(8):
                assert _enq(ring, i) is not None
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": 8}, daemon=True)
            t.start()
            got = _poll_all(ring, 8)
            t.join(60)
            assert not t.is_alive()
            # Verdicts flowed the whole time ...
            assert sorted(got) == list(range(8))
            for i in range(8):
                assert got[i][0] & 3 == _want(i), i
            time.sleep(0.25)
            lv = ring.liveness()
            # ... yet the heartbeat never re-stamped, so its age is
            # exactly what a PINGOO_SIDECAR_TIMEOUT_MS detector sees:
            # well past the 500 ms default by now (serving took >250 ms
            # of XLA compile alone).
            assert lv["heartbeat_ms"] == hb0
            assert lv["now_ms"] - lv["heartbeat_ms"] >= 200
        finally:
            sidecar.stop()
            ring.close()


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestDegradationLadder:
    """Ladder state machine with an injected clock — no sleeping."""

    def _ladder(self, clk, **kw):
        from pingoo_tpu.engine.ladder import DegradationLadder

        return DegradationLadder("test", clock=clk, **kw)

    def test_demote_probe_repromote(self):
        clk = _FakeClock()
        lad = self._ladder(clk, base_backoff_s=1.0)
        assert lad.try_rung("device")
        lad.note_failure("device", RuntimeError("boom"))
        assert not lad.healthy("device")
        assert lad.demoted() == ["device"]
        assert not lad.try_rung("device")   # backoff window closed
        clk.t = 1.0
        assert lad.try_rung("device")       # the probe
        assert not lad.try_rung("device")   # one probe per window
        lad.note_success("device")
        assert lad.healthy("device")
        assert lad.try_rung("device") and lad.try_rung("device")
        assert lad.demoted() == []

    def test_backoff_doubles_and_caps(self):
        clk = _FakeClock()
        lad = self._ladder(clk, base_backoff_s=1.0, max_backoff_s=4.0)
        lad.note_failure("dfa", RuntimeError("1"))
        assert lad.snapshot()["dfa"]["backoff_s"] == 1.0
        lad.note_failure("dfa", RuntimeError("2"))
        assert lad.snapshot()["dfa"]["backoff_s"] == 2.0
        lad.note_failure("dfa", RuntimeError("3"))
        lad.note_failure("dfa", RuntimeError("4"))
        assert lad.snapshot()["dfa"]["backoff_s"] == 4.0  # capped
        # Re-promotion resets to base for the next incident.
        lad.note_success("dfa")
        assert lad.snapshot()["dfa"]["backoff_s"] == 1.0

    def test_snapshot_counts_errors_and_demotions(self):
        clk = _FakeClock()
        lad = self._ladder(clk)
        lad.note_success("mesh")  # no-op while healthy
        snap0 = lad.snapshot()["mesh"]
        assert snap0["healthy"] and snap0["errors"] == 0 \
            and snap0["demotions"] == 0
        lad.note_failure("mesh", ValueError("shard"))
        lad.note_failure("mesh", ValueError("shard again"))
        clk.t = 100.0
        assert lad.try_rung("mesh")
        lad.note_success("mesh")
        lad.note_failure("mesh", ValueError("relapse"))
        snap = lad.snapshot()["mesh"]
        assert snap["errors"] == 3
        assert snap["demotions"] == 2  # healthy->demoted transitions
        assert snap["fallback"] == "single-device"
        assert "relapse" in snap["last_error"]


class TestChaosInjector:
    def test_spec_parses_every_fault(self):
        from pingoo_tpu.obs.chaos import ChaosInjector

        c = ChaosInjector("kill,pause:50:2,heartbeat_freeze,"
                          "stall:encode:5,xla_error:3,verdict_full:2")
        assert c.active
        assert c.kill_after == 1       # default N
        assert c.pause_ms == 50 and c.pause_after == 2
        assert c.freeze_heartbeat
        assert c.stalls == {"encode": 5.0}
        assert c.xla_error_at == 3
        assert c.verdict_full_budget == 2

    def test_malformed_spec_raises(self):
        from pingoo_tpu.obs.chaos import ChaosInjector

        for bad in ("bogus", "pause", "stall:encode", "kill:x"):
            with pytest.raises(ValueError):
                ChaosInjector(bad)

    def test_dormant_without_env(self, monkeypatch):
        from pingoo_tpu.obs.chaos import ChaosInjector

        monkeypatch.delenv("PINGOO_CHAOS", raising=False)
        c = ChaosInjector.from_env()
        assert not c.active
        c.on_batch_done(100)           # would SIGKILL if armed
        c.maybe_xla_error(100)
        c.stage("encode")
        assert not c.verdict_full()
        assert not c.heartbeat_frozen()

    def test_verdict_full_budget_decrements(self):
        from pingoo_tpu.obs.chaos import ChaosInjector

        c = ChaosInjector("verdict_full:2")
        assert c.verdict_full() and c.verdict_full()
        assert not c.verdict_full()


@needs_jax
class TestLadderRoundTrip:
    def test_device_fault_demotes_then_repromotes_bit_identical(
            self, tmp_path, monkeypatch):
        # Private plan: dfa demotion mutates plan.dfa_default_mode.
        plan = _make_plan()
        monkeypatch.setenv("PINGOO_CHAOS", "xla_error:1")
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=16)
        monkeypatch.delenv("PINGOO_CHAOS")
        try:
            n1 = 16
            for i in range(n1):
                assert _enq(ring, i) is not None
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": n1}, daemon=True)
            t.start()
            got = _poll_all(ring, n1)
            t.join(60)
            assert not t.is_alive()
            # The injected device fault fired and demoted a rung ...
            assert "xla" in sidecar.chaos._fired
            assert sidecar.ladder.demoted()
            snap = sidecar.ladder.snapshot()
            assert sum(r["errors"] for r in snap.values()) >= 1
            # ... and the fallback rung served bit-identical verdicts.
            assert sorted(got) == list(range(n1))
            for i in range(n1):
                assert got[i][0] & 3 == _want(i), i

            # Past the base backoff window the next dispatch probes the
            # demoted rung; the fault was one-shot, so the probe
            # succeeds and re-promotes.
            time.sleep(1.1)
            for i in range(n1, 2 * n1):
                assert _enq(ring, i) is not None
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": 2 * n1},
                                 daemon=True)
            t.start()
            got2 = _poll_all(ring, n1)
            t.join(60)
            assert not t.is_alive()
            assert sidecar.ladder.demoted() == []
            assert sorted(got2) == list(range(n1, 2 * n1))
            assert all(len(a) == 1 for a in got2.values())
            for i in range(n1, 2 * n1):
                assert got2[i][0] & 3 == _want(i), i
        finally:
            sidecar.stop()
            ring.close()


@needs_jax
class TestSigtermDrain:
    def test_stop_drains_inflight_and_pending(self, tmp_path, plan):
        """stop() is the SIGTERM drain path (host/server.py installs
        the handler): every ticket dequeued before the stop must still
        get a verdict — pending accumulation AND in-flight pipeline
        batches flush — and the posted floor must catch the dequeue
        cursor so the next epoch reconciles nothing."""
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=8,
                              pipeline_depth=3)
        try:
            n = 64
            for i in range(n):
                assert _enq(ring, i) is not None
            t = threading.Thread(target=sidecar.run, daemon=True)
            t.start()
            deadline = time.monotonic() + 120
            while ring.liveness()["req_tail"] == 0:
                assert time.monotonic() < deadline, "nothing dequeued"
                time.sleep(0.001)
            sidecar.stop(join_timeout_s=120)
            t.join(10)
            assert not t.is_alive()
            lv = ring.liveness()
            served = lv["req_tail"]
            assert served >= 1
            assert lv["posted_floor"] == served  # zero orphans left
            got = _poll_all(ring, served)
            assert sorted(got) == list(range(served))
            assert all(len(a) == 1 for a in got.values())
            for i in range(served):
                assert got[i][0] & 3 == _want(i), i
        finally:
            sidecar.stop()
            ring.close()
