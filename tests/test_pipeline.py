"""Zero-copy pipelined executor (ISSUE 9, docs/EXECUTOR.md).

Covers the tentpole's bit-identity contract and the satellites:

  * `StagingEncoder.encode_requests` / `encode_slots` must be
    value-identical to the legacy allocate-per-batch chain
    (encode_requests/slots_to_arrays -> bucket_arrays -> pad_batch)
    across seeds, odd batch sizes, overflow rows, ring wraparound and
    spill slots — the staged arrays go straight to the device, so any
    divergence is a served-verdict divergence.
  * PINGOO_PIPELINE=off|on verdict parity on both planes (the Python
    listener service end-to-end, the ring sidecar through real shm
    rings) with the ParityAuditor sampling the zero-copy path and the
    fault-injection knob proving an injected divergence is observable.
  * The stage-aware CostModel feed, the PipelineStats overlap
    bookkeeping, the per-stage fail-open budget, and the analyze-lint
    hot registration of the new executor path (mutation proof).
"""

import asyncio
import os
import random
import threading
import time

import numpy as np
import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.engine.batch import (
    RequestBatch,
    RequestTuple,
    StagingEncoder,
    bucket_arrays,
    bucket_len,
    encode_requests,
    pad_batch,
    pow2_batch_size,
)
from pingoo_tpu.obs.pipeline import PIPELINE_EXEC_STAGES, PipelineStats
from pingoo_tpu.obs.registry import MetricRegistry
from pingoo_tpu.sched.scheduler import (
    PIPELINE_COST_STAGES,
    STAGE_SEED_SPLIT,
    CostModel,
)
from test_parity import LISTS, RULE_SOURCES, make_rules, random_requests

HAVE_NATIVE = native_ring.ensure_built()
needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native toolchain unavailable")


def _legacy_encode(reqs, specs, pad_to):
    """The allocate-per-batch reference chain the staging encoder
    replaces: fresh matrices, bucketed copy, zero-row concatenate."""
    raw = encode_requests(reqs, specs)
    bucketed = RequestBatch(size=raw.size,
                            arrays=bucket_arrays(raw.arrays),
                            overflow=raw.overflow)
    return pad_batch(bucketed, pad_to)


def _assert_batches_equal(staged, legacy, with_overflow=True):
    assert staged.size == legacy.size
    assert set(staged.arrays) == set(legacy.arrays)
    for key in legacy.arrays:
        a, b = staged.arrays[key], legacy.arrays[key]
        assert a.shape == b.shape, (key, a.shape, b.shape)
        assert a.dtype == b.dtype, (key, a.dtype, b.dtype)
        assert np.array_equal(a, b), key
    if with_overflow:
        assert np.array_equal(staged.overflow, legacy.overflow)


class TestStagingEncoderRequests:
    """encode_requests bit-identity vs the legacy tuple chain."""

    def test_bit_identity_across_seeds_and_odd_sizes(self):
        enc = StagingEncoder(64)
        for seed, n in ((0, 1), (1, 13), (2, 37), (3, 64), (7, 41)):
            reqs = random_requests(random.Random(seed), n)
            pad = pow2_batch_size(n, 64)
            staged = enc.encode_requests(reqs, pad_to=pad)
            _assert_batches_equal(staged,
                                  _legacy_encode(reqs, None, pad))

    def test_full_batch_stays_staged(self):
        """A batch whose size is already the padded pow2 size must
        round-trip too (the executor passes staged=True, so the legacy
        re-bucket must never silently run)."""
        enc = StagingEncoder(32)
        reqs = random_requests(random.Random(5), 32)
        staged = enc.encode_requests(reqs, pad_to=32)
        _assert_batches_equal(staged, _legacy_encode(reqs, None, 32))

    def test_overflow_rows_match_legacy(self):
        specs = {"host": 16, "url": 32, "path": 16, "method": 16,
                 "user_agent": 16, "country": 2}
        enc = StagingEncoder(16, field_specs=specs)
        reqs = [
            RequestTuple(host="h.test", url="/" + "a" * 64,
                         path="/" + "b" * 40, user_agent="ua",
                         ip="10.0.0.1"),
            RequestTuple(host="x" * 20, url="/ok", path="/ok",
                         user_agent="u" * 16, ip="10.0.0.2"),
            RequestTuple(host="fits", url="/s", path="/s",
                         user_agent="ua", ip="not-an-ip"),
        ]
        pad = pow2_batch_size(len(reqs), 16)
        staged = enc.encode_requests(reqs, pad_to=pad)
        legacy = _legacy_encode(reqs, specs, pad)
        _assert_batches_equal(staged, legacy)
        assert staged.overflow[:3].tolist() == [True, True, False]
        # full-cap field: exactly at capacity is NOT overflow
        assert int(staged.arrays["user_agent_len"][1]) == 16

    def test_rotation_preserves_inflight_views(self):
        """nbuf buffer sets: a batch's views must stay intact for the
        next nbuf-1 checkouts (depth batches in flight + one filling),
        then the set recycles."""
        enc = StagingEncoder(16, nbuf=3)
        a_reqs = random_requests(random.Random(11), 5)
        a = enc.encode_requests(a_reqs, pad_to=8)
        frozen = {k: v.copy() for k, v in a.arrays.items()}
        for seed in (12, 13):  # nbuf - 1 more checkouts
            enc.encode_requests(random_requests(random.Random(seed), 7),
                                pad_to=8)
        for k, v in frozen.items():
            assert np.array_equal(a.arrays[k], v), k
        # One more checkout lands back on A's buffer set.
        d = enc.encode_requests(random_requests(random.Random(14), 5),
                                pad_to=8)
        assert any(np.shares_memory(d.arrays[k], a.arrays[k])
                   for k in d.arrays)

    def test_bad_shapes_raise(self):
        enc = StagingEncoder(16)
        reqs = random_requests(random.Random(0), 4)
        with pytest.raises(ValueError):
            enc.encode_requests([], pad_to=8)
        with pytest.raises(ValueError):
            enc.encode_requests(reqs, pad_to=2)  # pad below batch size
        with pytest.raises(ValueError):
            enc.encode_requests(reqs, pad_to=32)  # beyond max_batch


@needs_native
class TestStagingEncoderSlots:
    """encode_slots bit-identity vs slots_to_arrays -> bucket -> pad,
    through a real shm ring (wraparound included)."""

    def _slot_caps(self):
        caps = dict(native_ring.FIELD_CAPS)
        caps["country"] = 2
        return caps

    def _enqueue(self, ring, i, url=None):
        body = (url if url is not None
                else f"/p{i}?q={'x' * (i % 90)}".encode())
        return ring.enqueue(
            method=b"GET" if i % 3 else b"POST",
            host=f"h{i % 7}.test".encode(), path=body, url=body,
            user_agent=f"ua-{i % 5}".encode(),
            ip=b"\x00" * 10 + b"\xff\xff" + bytes(
                [10, i % 256, (i * 7) % 256, 1]),
            port=1000 + i, asn=64500 + (i % 9),
            country=b"FR" if i % 2 else b"DE")

    def _legacy_slots(self, slots, pad_to):
        raw = RequestBatch(size=len(slots),
                           arrays=native_ring.slots_to_arrays(slots))
        return pad_batch(
            RequestBatch(size=len(slots),
                         arrays=bucket_arrays(raw.arrays)), pad_to)

    def test_bit_identity_across_wraparound(self, tmp_path):
        ring = native_ring.Ring(str(tmp_path / "ring"), capacity=32,
                                create=True)
        enc = StagingEncoder(32, field_specs=self._slot_caps())
        out = np.zeros(32, dtype=native_ring.REQUEST_SLOT_DTYPE)
        try:
            i = 0
            # 4 cycles of 20 on a 32-slot ring force head wraparound.
            for cycle in range(4):
                for _ in range(20):
                    assert self._enqueue(ring, i) is not None
                    i += 1
                n = ring.dequeue_batch_into(out)
                assert n == 20
                slots = out[:n]
                pad = pow2_batch_size(n, 32)
                staged = enc.encode_slots(slots, pad_to=pad)
                _assert_batches_equal(staged,
                                      self._legacy_slots(slots, pad),
                                      with_overflow=False)
                assert staged.overflow is None
        finally:
            ring.close()

    def test_dequeue_into_matches_scratch_dequeue(self, tmp_path):
        """The zero-copy bulk dequeue must land the same slot bytes the
        legacy scratch+copy path returns."""
        ring = native_ring.Ring(str(tmp_path / "ring"), capacity=32,
                                create=True)
        try:
            for i in range(9):
                self._enqueue(ring, i)
            legacy = ring.dequeue_batch(32)
            for i in range(9, 18):
                self._enqueue(ring, i)
            out = np.zeros(32, dtype=native_ring.REQUEST_SLOT_DTYPE)
            n = ring.dequeue_batch_into(out)
            assert len(legacy) == n == 9
            for field in ("method", "host", "path", "url", "user_agent",
                          "ip", "asn", "remote_port", "country"):
                # Same round-robin request shape at offset 9: compare
                # the content-generating fields modulo their cycle.
                assert out[:n]["asn"].tolist() == [
                    64500 + ((9 + k) % 9) for k in range(9)]
            assert out[:n]["ticket"].tolist() == list(range(9, 18))
        finally:
            ring.close()

    def test_truncated_and_spill_slots_match_legacy(self, tmp_path):
        """Rows past the 2048-byte slot cap (flags + spill_idx set)
        must encode identically through both chains — the spill
        re-interpretation happens downstream, off the encode path."""
        ring = native_ring.Ring(str(tmp_path / "ring"), capacity=32,
                                create=True)
        enc = StagingEncoder(32, field_specs=self._slot_caps())
        try:
            huge = b"/" + b"A" * 3000  # past the 2048 slot cap
            self._enqueue(ring, 0, url=huge)
            self._enqueue(ring, 1)
            out = np.zeros(32, dtype=native_ring.REQUEST_SLOT_DTYPE)
            n = ring.dequeue_batch_into(out)
            assert n == 2
            slots = out[:n]
            assert (slots["flags"][0]
                    & native_ring.SLOT_FLAG_TRUNCATED) != 0
            staged = enc.encode_slots(slots, pad_to=8)
            _assert_batches_equal(staged, self._legacy_slots(slots, 8),
                                  with_overflow=False)
            for j in np.nonzero(
                    slots["spill_idx"] != native_ring.SPILL_NONE)[0]:
                ring.spill_release(int(slots["spill_idx"][j]))
        finally:
            ring.close()


class TestPipelineStats:
    """Overlap bookkeeping: host stages of one batch overlapping a
    DIFFERENT batch's compute window, counted exactly once."""

    def _stats(self, depth=3):
        return PipelineStats("test", depth, registry=MetricRegistry())

    def test_enter_exit_inflight_and_mode_counters(self):
        ps = self._stats(depth=2)
        s1 = ps.enter("on")
        s2 = ps.enter("off")
        assert s2 == s1 + 1
        snap = ps.snapshot()
        assert snap["inflight"] == 2 and snap["depth"] == 2
        assert snap["batches"] == {"off": 1, "on": 1}
        ps.exit()
        ps.exit()
        assert ps.snapshot()["inflight"] == 0

    def test_cross_slot_host_compute_overlap_scores(self):
        ps = self._stats()
        t = time.monotonic()
        s1, s2 = ps.enter(), ps.enter()
        # slot2 host dispatch [t, t+0.1]; slot1 compute [t+0.05, t+0.15]
        ps.note_stage(s2, "dispatch", t, t + 0.1)
        assert ps.overlap_events == 0  # no compute interval stored yet
        ps.note_stage(s1, "compute", t + 0.05, t + 0.15)
        assert ps.overlap_events == 1
        # ratio = overlap / compute window = 0.05 / 0.1
        assert ps.snapshot()["overlap_ratio"] == pytest.approx(
            0.5, abs=0.01)

    def test_same_slot_intervals_never_pair(self):
        ps = self._stats()
        t = time.monotonic()
        s1 = ps.enter()
        ps.note_stage(s1, "encode", t, t + 0.1)
        ps.note_stage(s1, "compute", t, t + 0.1)
        assert ps.overlap_events == 0

    def test_disjoint_intervals_never_pair(self):
        ps = self._stats()
        t = time.monotonic()
        s1, s2 = ps.enter(), ps.enter()
        ps.note_stage(s1, "dispatch", t, t + 0.05)
        ps.note_stage(s2, "compute", t + 0.06, t + 0.1)
        assert ps.overlap_events == 0

    def test_negative_and_unknown_stages_ignored(self):
        ps = self._stats()
        s = ps.enter()
        t = time.monotonic()
        ps.note_stage(s, "compute", t, t - 1.0)  # negative duration
        ps.note_stage(s, "warp", t, t + 0.1)  # unknown stage
        assert ps.overlap_events == 0
        snap = ps.snapshot()
        assert set(snap["stage_occupancy"]) == set(PIPELINE_EXEC_STAGES)


class TestCostModelStages:
    """Stage-aware EWMA feed (ISSUE 9 satellite): estimates decompose
    per executor stage once observations land."""

    def test_pure_seed_estimate_matches_stage_sum(self):
        cm = CostModel(max_batch=1024, seed_ms=8.0)
        # No stage observations: estimate_stage falls back to the seed
        # split, and the splits sum to the whole-batch estimate.
        whole = cm.estimate(512)
        parts = sum(cm.estimate_stage(s, 512)
                    for s in PIPELINE_COST_STAGES)
        assert parts == pytest.approx(whole)
        assert sum(STAGE_SEED_SPLIT.values()) == pytest.approx(1.0)

    def test_observed_stages_drive_the_estimate(self):
        cm = CostModel(max_batch=1024, seed_ms=8.0)
        for _ in range(40):
            cm.observe_stage("encode", 512, 1.0)
            cm.observe_stage("dispatch", 512, 2.0)
            cm.observe_stage("compute", 512, 5.0)
        assert cm.estimate_stage("compute", 512) == pytest.approx(
            5.0, rel=0.05)
        assert cm.estimate(512) == pytest.approx(8.0, rel=0.05)

    def test_unobserved_stage_falls_back_to_split_share(self):
        cm = CostModel(max_batch=1024, seed_ms=10.0)
        cm.observe_stage("compute", 256, 3.0)
        base = cm.estimate(256) - 3.0
        expect = (STAGE_SEED_SPLIT["encode"]
                  + STAGE_SEED_SPLIT["dispatch"]) * cm.estimate_stage(
                      "compute", 256) / 3.0 * 0  # doc: see next asserts
        del expect
        # encode/dispatch fall back to their seed-split share of the
        # whole-batch baseline.
        assert cm.estimate_stage("encode", 256) == pytest.approx(
            STAGE_SEED_SPLIT["encode"] * cm._baseline(256))
        assert base == pytest.approx(
            (STAGE_SEED_SPLIT["encode"] + STAGE_SEED_SPLIT["dispatch"])
            * cm._baseline(256))

    def test_unknown_stage_and_negative_ms_ignored(self):
        cm = CostModel(max_batch=64, seed_ms=5.0)
        cm.observe_stage("warp", 32, 1.0)
        cm.observe_stage("encode", 32, -1.0)
        assert cm.snapshot()["stage_ewma_ms"] == {}

    def test_snapshot_carries_stage_ewma(self):
        cm = CostModel(max_batch=64, seed_ms=5.0)
        cm.observe_stage("encode", 32, 1.5)
        snap = cm.snapshot()
        assert snap["stage_ewma_ms"]["encode"] == {32: 1.5}


def _make_plan():
    from pingoo_tpu.compiler import compile_ruleset

    return compile_ruleset(make_rules(RULE_SOURCES), LISTS)


def _drive_service(plan, reqs, env, max_batch=32):
    """Boot a VerdictService under `env`, evaluate `reqs` in concurrent
    waves (so multiple batches are in flight), return verdicts+snaps."""
    from pingoo_tpu.engine.service import VerdictService

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        async def go():
            svc = VerdictService(plan, LISTS, use_device=True,
                                 max_batch=max_batch, max_wait_us=200)
            await svc.start()
            verdicts = []
            wave = max_batch - 7  # odd wave size: partial batches too
            for w in range(0, len(reqs), wave):
                verdicts.extend(await asyncio.gather(
                    *[svc.evaluate(r) for r in reqs[w:w + wave]]))
            snap = svc.pipeline_snapshot()
            cost = svc.sched.cost.snapshot()
            await svc.stop()
            return verdicts, snap, cost

        return asyncio.run(go())
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


@pytest.mark.slow
class TestPythonPlaneModeParity:
    def test_off_on_verdict_parity_and_telemetry(self):
        plan = _make_plan()
        reqs = random_requests(random.Random(99), 240)
        v_on, snap_on, cost_on = _drive_service(
            plan, reqs, {"PINGOO_PIPELINE": "on",
                         "PINGOO_PIPELINE_DEPTH": "3"})
        v_off, snap_off, _ = _drive_service(
            plan, reqs, {"PINGOO_PIPELINE": "off"})
        assert len(v_on) == len(v_off) == len(reqs)
        for a, b in zip(v_on, v_off):
            assert a.action == b.action
            assert a.verified_block == b.verified_block
            assert np.array_equal(a.matched, b.matched)
        assert snap_on["mode"] == "on" and snap_off["mode"] == "off"
        assert snap_on["batches"].get("on", 0) > 0
        assert snap_off["batches"].get("off", 0) > 0
        # Stage-aware cost feed landed per-stage EWMAs (satellite).
        assert cost_on.get("stage_ewma_ms", {}).get("encode")
        assert cost_on.get("stage_ewma_ms", {}).get("compute")


class TestStageBudget:
    """Per-stage fail-open budget slices (tentpole part 3)."""

    def _svc(self, monkeypatch, failopen, deadline_ms="2.0"):
        from pingoo_tpu.engine.service import VerdictService

        monkeypatch.setenv("PINGOO_SCHED_FAILOPEN", failopen)
        monkeypatch.setenv("PINGOO_DEADLINE_MS", deadline_ms)
        monkeypatch.setenv("PINGOO_PIPELINE", "on")
        return VerdictService(_make_plan(), LISTS, use_device=False,
                              max_batch=16)

    def test_serve_policy_never_raises(self, monkeypatch):
        svc = self._svc(monkeypatch, "serve")
        svc._check_stage_budget("encode", time.monotonic() - 5.0)

    def test_budget_overrun_raises_with_stage(self, monkeypatch):
        from pingoo_tpu.engine.service import _StageBudgetExceeded

        svc = self._svc(monkeypatch, "allow")
        # Launched 5s ago: far past 45% of the 2ms deadline.
        with pytest.raises(_StageBudgetExceeded) as exc:
            svc._check_stage_budget("encode", time.monotonic() - 5.0)
        assert exc.value.stage == "encode"
        assert exc.value.elapsed_ms > 1000
        # Fresh launch: within budget, no raise.
        svc._check_stage_budget("encode", time.monotonic())
        # Stages without a budget slice never raise.
        svc._check_stage_budget("compute", time.monotonic() - 5.0)
        # No launch timestamp (legacy callers): no raise.
        svc._check_stage_budget("encode", None)

    @pytest.mark.slow
    def test_interpret_failopen_serves_identical_verdicts(self):
        """An impossible deadline + failopen=interpret trips the encode
        budget on every batch; _failopen_batch must still resolve every
        future, through the interpreter, with parity-identical actions."""
        plan = _make_plan()
        reqs = random_requests(random.Random(17), 40)
        v_fo, _, _ = _drive_service(
            plan, reqs, {"PINGOO_PIPELINE": "on",
                         "PINGOO_SCHED_FAILOPEN": "interpret",
                         "PINGOO_DEADLINE_MS": "0.000001"},
            max_batch=16)
        v_ref, _, _ = _drive_service(
            plan, reqs, {"PINGOO_PIPELINE": "on",
                         "PINGOO_SCHED_FAILOPEN": "serve",
                         "PINGOO_DEADLINE_MS": "2.0"},
            max_batch=16)
        assert len(v_fo) == len(v_ref) == len(reqs)
        for a, b in zip(v_fo, v_ref):
            assert a.action == b.action


@needs_native
@pytest.mark.slow
class TestSidecarModeParity:
    """PINGOO_PIPELINE off/on through real shm rings: identical verdict
    checksums, plus the ParityAuditor auditing the zero-copy path with
    the fault-injection proof."""

    def _drive(self, tmp_path, tag, env, n=300, parity_sample=None):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.native_ring import Ring, RingSidecar

        # Drop the trailing always-true rule (and use a non-curl UA and
        # an unlisted client IP below) so benign rows genuinely match
        # NOTHING: the stream serves mixed allow/block verdicts, which
        # makes the off/on checksum comparison meaningful and gives the
        # fault-inject oracle flip a lane-visible allow→block edge.
        plan = compile_ruleset(make_rules(RULE_SOURCES[:23]), LISTS)
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            ring = Ring(str(tmp_path / f"ring-{tag}"), capacity=256,
                        create=True)
            sidecar = RingSidecar(ring, plan, LISTS, max_batch=32,
                                  pipeline_depth=3)
            th = threading.Thread(target=sidecar.run, daemon=True)
            th.start()
            rng = random.Random(23)
            # Fix the stream up front: a full-ring enqueue retries the
            # SAME request, so both modes serve identical payloads no
            # matter how the enqueue/poll race interleaves.
            paths = [b"/admin/.env" if rng.random() < 0.3
                     else f"/ok/{k}".encode() for k in range(n)]
            actions = {}
            sent = 0
            t_deadline = time.time() + 120
            while len(actions) < n and time.time() < t_deadline:
                if sent < n:
                    path = paths[sent]
                    t = ring.enqueue(
                        method=b"GET", host=b"h.test", path=path,
                        url=path, user_agent=b"Mozilla/5.0 t",
                        ip=b"\x00" * 10 + b"\xff\xff" + bytes(
                            [172, 16, sent % 256, 9]),
                        port=4000 + sent, asn=64496, country=b"FR")
                    if t is not None:
                        sent += 1
                v = ring.poll_verdict()
                while v is not None:
                    ticket, action, _ = v
                    actions[ticket] = action
                    v = ring.poll_verdict()
            parity = sidecar.parity
            if parity is not None:
                parity.flush(30)
                checked = parity.checked_total.value
                mismatches = parity.mismatch_total.value
            else:
                checked = mismatches = 0
            sidecar.stop()
            ring.close()
            assert len(actions) == n, f"{tag}: {len(actions)}/{n}"
            return ([actions[t] for t in sorted(actions)],
                    checked, mismatches)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_off_on_checksum_parity_with_auditor(self, tmp_path):
        base = {"PINGOO_PARITY_SAMPLE": "1",
                "PINGOO_PROVENANCE": "1"}
        on, checked_on, mm_on = self._drive(
            tmp_path, "on", {**base, "PINGOO_PIPELINE": "on"})
        off, checked_off, mm_off = self._drive(
            tmp_path, "off", {**base, "PINGOO_PIPELINE": "off"})
        assert on == off  # identical served actions, ticket-ordered
        assert len(set(on)) > 1  # mixed allow/block, not a uniform stream
        # The auditor audited the zero-copy plane and found it clean.
        assert checked_on > 0 and mm_on == 0
        assert checked_off > 0 and mm_off == 0

    def test_fault_injection_is_observable_through_zero_copy(
            self, tmp_path):
        """PINGOO_PARITY_FAULT_INJECT flips the ORACLE for matching
        paths: served verdicts stay identical, and the auditor must
        surface the divergence even when its contexts come from the
        snapshotted staging views (the zero-copy audit path)."""
        _, checked, mismatches = self._drive(
            tmp_path, "fault",
            {"PINGOO_PIPELINE": "on", "PINGOO_PARITY_SAMPLE": "1",
             "PINGOO_PROVENANCE": "1",
             "PINGOO_PARITY_FAULT_INJECT": "/ok/"})
        assert checked > 0
        assert mismatches > 0


class TestLintHotRegistry:
    """ISSUE 9 satellite: the executor path is registered hot, with a
    mutation proof that a fresh allocation there fails `make analyze`."""

    def test_executor_functions_registered_hot(self):
        from tools.analyze import lint_config

        for fn in (
            "pingoo_tpu/engine/batch.py::StagingEncoder.encode_requests",
            "pingoo_tpu/engine/batch.py::StagingEncoder.encode_slots",
            "pingoo_tpu/engine/service.py::"
            "VerdictService._check_stage_budget",
            "pingoo_tpu/sched/scheduler.py::CostModel.observe_stage",
            "pingoo_tpu/sched/scheduler.py::CostModel.estimate_stage",
            "pingoo_tpu/sched/scheduler.py::Scheduler.observe_stage_cost",
            "pingoo_tpu/obs/pipeline.py::PipelineStats.note_stage",
        ):
            assert fn in lint_config.HOT_FUNCTIONS, fn

    def test_current_tree_is_clean(self):
        from tools.analyze import lint

        findings, warnings = lint.lint_paths()
        assert findings == [], "\n".join(str(f) for f in findings)
        assert warnings == [], "\n".join(warnings)

    def test_mutated_staging_alloc_fails_lint(self):
        """Mutation proof: a fresh np.zeros inside the staged encode
        (the buffers exist to be REUSED) must fail the hot-alloc lint."""
        from tools.analyze import REPO_ROOT, lint

        with open(os.path.join(REPO_ROOT, "pingoo_tpu", "engine",
                               "batch.py")) as f:
            src = f.read()
        marker = "    def encode_slots(self, slots: np.ndarray,"
        assert marker in src
        mutated = src.replace(
            marker,
            "    def encode_slots(self, slots: np.ndarray,\n"
            "                     _leak=None,",
            1).replace(
            "        arrays: dict = {}\n"
            "        for field, len_key in SLOT_LEN_KEYS.items():",
            "        arrays: dict = {}\n"
            "        scratch = np.zeros((len(slots), 4))\n"
            "        for field, len_key in SLOT_LEN_KEYS.items():",
            1)
        assert "scratch = np.zeros" in mutated
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/engine/batch.py")
        assert any(f.rule == "hot-alloc" for f in findings), findings

    def test_mutated_budget_sync_fails_lint(self):
        """The budget check is pure float math between stages; a
        device materialization there must fail the lint."""
        from tools.analyze import REPO_ROOT, lint

        with open(os.path.join(REPO_ROOT, "pingoo_tpu", "engine",
                               "service.py")) as f:
            src = f.read()
        needle = "        elapsed_ms = (time.monotonic() - t_launch) * 1e3"
        assert needle in src
        mutated = src.replace(
            needle,
            needle + "\n        _probe = np.asarray(t_launch)", 1)
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/engine/service.py")
        assert any(f.rule == "sync-asarray-hot" for f in findings), \
            findings


# -- ISSUE 12: device-resident megastep -----------------------------------


class TestDeviceInputQueue:
    """Double-buffered device input stacks: the fill/trim/scrub
    invariants that keep the shipped window bit-identical to the
    batches that staged into it."""

    def _batch(self, seed, n, pad):
        return _legacy_encode(random_requests(random.Random(seed), n),
                              None, pad)

    def test_fill_and_device_stack_round_trip(self):
        from pingoo_tpu.engine.batch import DeviceInputQueue

        q = DeviceInputQueue(4, 32)
        buf = q.checkout()
        batches = [self._batch(s, 32, 32) for s in (0, 1, 2)]
        for j, b in enumerate(batches):
            q.fill_slice(buf, j, b.arrays, b.size, epoch=7)
        stacked, nv, ep = q.device_stack(buf, 3)
        assert np.asarray(nv).tolist() == [32, 32, 32]
        assert np.asarray(ep).tolist() == [7, 7, 7]
        for j, b in enumerate(batches):
            for name, arr in b.arrays.items():
                got = np.asarray(stacked[name])[j]
                if name.endswith("_bytes"):
                    w = arr.shape[1]
                    assert np.array_equal(got[:, :w], arr), name
                    # window-max trim beyond this slice's bucket must
                    # be zeros, never another slice's bytes
                    assert not got[:, w:].any(), name
                else:
                    assert np.array_equal(got, arr), name

    def test_widen_scrub_never_leaks_previous_window(self):
        """A wide window dirties the stacks; after the buffer set
        rotates back, a window whose early slice is NARROW and late
        slice WIDE must see zeros (not stale bytes) in the widened
        columns of the early slice."""
        from pingoo_tpu.engine.batch import DeviceInputQueue

        def mk(path):
            reqs = [RequestTuple(host="h.test", url="/u", path=path,
                                 user_agent="ua", ip="10.0.0.1")] * 4
            return _legacy_encode(reqs, None, 4)

        q = DeviceInputQueue(2, 4, nbuf=2)
        wide, narrow = mk("/" + "w" * 120), mk("/n")
        b0 = q.checkout()
        q.fill_slice(b0, 0, wide.arrays, 4, epoch=0)
        q.fill_slice(b0, 1, wide.arrays, 4, epoch=0)
        q.checkout()          # rotate to the other set ...
        b2 = q.checkout()     # ... and back onto the dirtied one
        assert b2 == b0
        q.fill_slice(b2, 0, narrow.arrays, 4, epoch=1)
        q.fill_slice(b2, 1, wide.arrays, 4, epoch=1)
        stacked, _, _ = q.device_stack(b2, 2)
        path = np.asarray(stacked["path_bytes"])
        w_narrow = narrow.arrays["path_bytes"].shape[1]
        assert path.shape[2] == wide.arrays["path_bytes"].shape[1]
        assert not path[0, :, w_narrow:].any(), \
            "stale wide-window bytes leaked into the narrow slice"

    def test_mismatched_row_buckets_raise(self):
        from pingoo_tpu.engine.batch import DeviceInputQueue

        q = DeviceInputQueue(2, 32)
        buf = q.checkout()
        q.fill_slice(buf, 0, self._batch(1, 8, 8).arrays, 8, epoch=0)
        with pytest.raises(ValueError, match="row bucket"):
            q.fill_slice(buf, 1, self._batch(2, 16, 16).arrays, 16,
                         epoch=0)

    def test_pad_to_ships_rung_shape_with_masked_slices(self):
        """pad_to quantizes the shipped leading dim (each distinct K is
        its own XLA compile): the padded slices must arrive with
        n_valid=0 — masked, whatever stale bytes the stacks held — and
        the filled slices bit-identical to the unpadded ship."""
        from pingoo_tpu.engine.batch import DeviceInputQueue

        q = DeviceInputQueue(4, 16, nbuf=2)
        stale = self._batch(9, 16, 16)
        b0 = q.checkout()
        for j in range(4):  # dirty all four slices of this buffer set
            q.fill_slice(b0, j, stale.arrays, 16, epoch=0)
        q.checkout()
        b2 = q.checkout()
        assert b2 == b0
        fresh = self._batch(10, 16, 16)
        q.fill_slice(b2, 0, fresh.arrays, 16, epoch=3)
        stacked, nv, ep = q.device_stack(b2, 1, pad_to=4)
        assert np.asarray(nv).tolist() == [16, 0, 0, 0]
        assert int(np.asarray(ep)[0]) == 3
        for name, arr in fresh.arrays.items():
            got = np.asarray(stacked[name])[0]
            if name.endswith("_bytes"):
                assert np.array_equal(got[:, :arr.shape[1]], arr), name
            else:
                assert np.array_equal(got, arr), name
        # pad_to never exceeds the queue's K and never trims below the
        # filled count
        assert np.asarray(q.device_stack(b2, 1, pad_to=9)[1]).shape == (4,)

    def test_slice_view_stable_across_one_rotation(self):
        """nbuf=3: a window's host views must survive the NEXT window's
        checkout+fill (its batches are still resolving while the next
        window stages) — the same contract the StagingEncoder holds."""
        from pingoo_tpu.engine.batch import DeviceInputQueue

        q = DeviceInputQueue(1, 16, nbuf=3)
        a = self._batch(3, 16, 16)
        b0 = q.checkout()
        q.fill_slice(b0, 0, a.arrays, 16, epoch=0)
        view = q.slice_view(b0, 0, 16)
        want = {k: v.copy() for k, v in view.items()}
        b1 = q.checkout()
        q.fill_slice(b1, 0, self._batch(4, 16, 16).arrays, 16, epoch=0)
        for name, arr in want.items():
            assert np.array_equal(view[name], arr), name


class TestMegastepKnobs:
    """Mode/K env parsing + the scheduler's megastep cost model."""

    def test_mode_resolution(self, monkeypatch):
        from pingoo_tpu.engine.verdict import _resolve_megastep_mode

        monkeypatch.delenv("PINGOO_MEGASTEP", raising=False)
        assert _resolve_megastep_mode() == "off"
        for mode in ("off", "auto", "force"):
            monkeypatch.setenv("PINGOO_MEGASTEP", mode)
            assert _resolve_megastep_mode() == mode
        monkeypatch.setenv("PINGOO_MEGASTEP", "warp")
        assert _resolve_megastep_mode() == "off"

    def test_k_ladder_is_pow2_and_capped(self, monkeypatch):
        from pingoo_tpu.engine.verdict import (megastep_k_cap,
                                               megastep_k_ladder)

        assert megastep_k_ladder(6) == [1, 2, 4]
        assert megastep_k_ladder(1) == [1]
        assert megastep_k_ladder(0) == [1]
        monkeypatch.setenv("PINGOO_MEGASTEP_K", "8")
        assert megastep_k_cap() == 8
        monkeypatch.setenv("PINGOO_MEGASTEP_K", "bogus")
        assert megastep_k_cap() >= 1

    def test_estimate_falls_back_to_amortization_model(self):
        cm = CostModel(max_batch=1024, seed_ms=8.0)
        for _ in range(40):
            cm.observe_stage("dispatch", 512, 2.0)
            cm.observe_stage("compute", 512, 5.0)
        # Unobserved (K, bucket): one dispatch + K compute walls.
        assert cm.estimate_megastep(4, 512) == pytest.approx(
            2.0 + 4 * 5.0, rel=0.05)
        # Observed wall wins over the model.
        for _ in range(40):
            cm.observe_megastep(4, 512, 9.0)
        assert cm.estimate_megastep(4, 512) == pytest.approx(
            9.0, rel=0.05)
        snap = cm.snapshot()
        assert snap["megastep_ewma_ms"]["4x512"] == pytest.approx(
            9.0, rel=0.05)

    def test_size_megastep_k_fits_deadline_slack(self):
        from pingoo_tpu.sched.scheduler import Scheduler, SchedulerConfig

        cfg = SchedulerConfig(max_batch=128, deadline_ms=50.0)
        s = Scheduler(cfg, plane="python")
        for _ in range(40):
            s.cost.observe_stage("dispatch", 128, 2.0)
            s.cost.observe_stage("compute", 128, 10.0)
        now = 100.0
        # Fresh admit: 50ms slack fits 2 + 4*10 = 42ms but not
        # 2 + 8*10 = 82ms.
        assert s.size_megastep_k([1, 2, 4, 8], 128, now, now) == 4
        # 25ms slack left: only K=2 (22ms) fits.
        assert s.size_megastep_k([1, 2, 4, 8], 128, now - 0.025,
                                 now) == 2
        # Budget blown: never below 1 (launch now, count the miss).
        assert s.size_megastep_k([1, 2, 4, 8], 128, now - 10.0,
                                 now) == 1


class TestMegastepProgramParity:
    """make_megastep_fn vs the per-batch programs it amortizes: the
    K-slice scan must be bit-identical to K separate dispatches,
    including masked odd tails (n_valid < rows)."""

    def test_matrix_kind_matches_per_batch_finish(self):
        from pingoo_tpu.engine.batch import DeviceInputQueue
        from pingoo_tpu.engine.verdict import (finish_batch,
                                               finish_megastep,
                                               make_megastep_fn,
                                               make_verdict_fn)

        plan = _make_plan()
        verdict_fn = make_verdict_fn(plan)
        mega = make_megastep_fn(plan, kind="matrix")
        q = DeviceInputQueue(4, 16, field_specs=plan.field_specs)
        # K=4 slices with odd tails: 16, 13, 16, 5 live rows.
        ns = (16, 13, 16, 5)
        batches = [
            _legacy_encode(random_requests(random.Random(40 + j), n),
                           plan.field_specs, 16)
            for j, n in enumerate(ns)]
        buf = q.checkout()
        for j, (n, b) in enumerate(zip(ns, batches)):
            q.fill_slice(buf, j, b.arrays, n, epoch=3)
        stacked, nv, ep = q.device_stack(buf, 4)
        out = mega.fn(plan.device_tables(), stacked, nv, ep)
        assert np.asarray(out[3]).tolist() == [3, 3, 3, 3]
        lists = dict(LISTS)
        offsets, slices = [], []
        off = 0
        for n in ns:
            slices.append((off, n))
            offsets.append(off)
            off += 16
        stitched = RequestBatch(size=off, arrays={
            name: np.concatenate(
                [np.asarray(stacked[name])[j] for j in range(4)])
            for name in stacked})
        got = finish_megastep(plan, out[0], slices, stitched, lists)
        for j, (n, b) in enumerate(zip(ns, batches)):
            dev = verdict_fn(plan.device_tables(), b.arrays, None)
            want = finish_batch(plan, dev, b, lists)
            got_rows = got[offsets[j]:offsets[j] + n]
            assert np.array_equal(got_rows, want[:n]), \
                f"slice {j} (n={n}) diverged from per-batch dispatch"


@pytest.mark.slow
class TestMegastepPythonPlaneParity:
    """PINGOO_MEGASTEP off|auto|force through the full service: `off`
    is the oracle, and every mode must serve identical verdicts."""

    def test_off_auto_force_bit_identity_and_telemetry(self):
        plan = _make_plan()
        reqs = random_requests(random.Random(77), 200)
        base = {"PINGOO_PIPELINE": "on", "PINGOO_PIPELINE_DEPTH": "3",
                "PINGOO_MEGASTEP_K": "4"}
        v_off, _, _ = _drive_service(
            plan, reqs, {**base, "PINGOO_MEGASTEP": "off"})
        v_force, snap_f, cost_f = _drive_service(
            plan, reqs, {**base, "PINGOO_MEGASTEP": "force"})
        v_auto, _, _ = _drive_service(
            plan, reqs, {**base, "PINGOO_MEGASTEP": "auto"})
        assert len(v_off) == len(v_force) == len(v_auto) == len(reqs)
        for a, b, c in zip(v_off, v_force, v_auto):
            assert a.action == b.action == c.action
            assert a.verified_block == b.verified_block \
                == c.verified_block
            assert np.array_equal(a.matched, b.matched)
            assert np.array_equal(a.matched, c.matched)
        # force actually ran megastep windows, and the telemetry
        # satellite saw them: K gauge, per-mode slices, cost EWMAs.
        mega = snap_f["megastep"]
        assert mega["windows"] > 0
        assert mega["slices"] >= mega["windows"]
        assert mega["slices_by_mode"].get("force", 0) > 0
        assert cost_f.get("megastep_ewma_ms")


@needs_native
@pytest.mark.slow
class TestMegastepSidecarParity:
    """off|force|auto through real shm rings: identical ticket-ordered
    actions (n=300 over a 256-capacity ring covers wraparound), live
    windows under force, and zero ruleset-epoch echo mismatches."""

    def _drive(self, tmp_path, tag, env, n=300):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.native_ring import Ring, RingSidecar

        plan = compile_ruleset(make_rules(RULE_SOURCES[:23]), LISTS)
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            ring = Ring(str(tmp_path / f"mring-{tag}"), capacity=256,
                        create=True)
            sidecar = RingSidecar(ring, plan, LISTS, max_batch=32,
                                  pipeline_depth=3)
            th = threading.Thread(target=sidecar.run, daemon=True)
            th.start()
            rng = random.Random(23)
            paths = [b"/admin/.env" if rng.random() < 0.3
                     else f"/ok/{k}".encode() for k in range(n)]
            actions = {}
            sent = 0
            t_deadline = time.time() + 120
            while len(actions) < n and time.time() < t_deadline:
                if sent < n:
                    path = paths[sent]
                    t = ring.enqueue(
                        method=b"GET", host=b"h.test", path=path,
                        url=path, user_agent=b"Mozilla/5.0 t",
                        ip=b"\x00" * 10 + b"\xff\xff" + bytes(
                            [172, 16, sent % 256, 9]),
                        port=4000 + sent, asn=64496, country=b"FR")
                    if t is not None:
                        sent += 1
                v = ring.poll_verdict()
                while v is not None:
                    ticket, action, _ = v
                    actions[ticket] = action
                    v = ring.poll_verdict()
            stats = sidecar.stats()
            sidecar.stop()
            ring.close()
            assert len(actions) == n, f"{tag}: {len(actions)}/{n}"
            return [actions[t] for t in sorted(actions)], stats
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_off_force_auto_checksum_parity(self, tmp_path):
        base = {"PINGOO_MEGASTEP_K": "4"}
        off, _ = self._drive(
            tmp_path, "off", {**base, "PINGOO_MEGASTEP": "off"})
        force, st_f = self._drive(
            tmp_path, "force", {**base, "PINGOO_MEGASTEP": "force"})
        auto, st_a = self._drive(
            tmp_path, "auto", {**base, "PINGOO_MEGASTEP": "auto"})
        assert len(set(off)) > 1  # mixed allow/block stream
        assert off == force
        assert off == auto
        assert st_f["megastep"]["mode"] == "force"
        assert st_f["megastep"]["windows"] > 0
        assert st_f["megastep"]["echo_mismatch"] == 0
        assert st_a["megastep"]["echo_mismatch"] == 0


def _prefix_plan(prefix):
    """One-rule plan blocking paths under `prefix` — swapping between
    two of these gives every ticket a phase-determined verdict."""
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    rules = [RuleConfig(
        name="blk", actions=(Action.BLOCK,),
        expression=compile_expression(
            f'http_request.path.starts_with("{prefix}")'))]
    return compile_ruleset(rules, {})


@needs_native
@pytest.mark.slow
class TestMegastepHotSwapBoundary:
    """ISSUE 11 x ISSUE 12: a hot-swap under PINGOO_MEGASTEP=force
    flips ONLY at a megastep-window boundary — every slice verdicts
    under the plan epoch it was staged with (zero epoch-echo
    mismatches), and each phase is bit-exact under ITS plan."""

    def test_swap_flips_at_window_boundary(self, tmp_path, monkeypatch):
        from pingoo_tpu.native_ring import Ring, RingSidecar

        monkeypatch.setenv("PINGOO_MEGASTEP", "force")
        monkeypatch.setenv("PINGOO_MEGASTEP_K", "4")
        ring = Ring(str(tmp_path / "ring-mswap"), capacity=256,
                    create=True)
        sidecar = RingSidecar(ring, _prefix_plan("/alpha"), {},
                              max_batch=16)
        n = 48

        def enq(i, phase):
            path = (b"/%s/%d" % (phase.encode(), i)) if i % 3 == 0 \
                else b"/ok/%d" % i
            return ring.enqueue(method=b"GET", host=b"r.test",
                                path=path, url=path,
                                user_agent=b"Mozilla/5.0")

        def poll_all(need, timeout=120.0):
            got: dict = {}
            deadline = time.monotonic() + timeout
            while sum(len(v) for v in got.values()) < need and \
                    time.monotonic() < deadline:
                v = ring.poll_verdict()
                if v is None:
                    time.sleep(0.002)
                    continue
                got.setdefault(v[0], []).append(v[1])
            return got

        try:
            worker = threading.Thread(target=sidecar.run, daemon=True)
            worker.start()
            for i in range(n):
                assert enq(i, "alpha") is not None
            got_a = poll_all(n)

            handle = sidecar.request_swap(_prefix_plan("/beta"))
            assert handle.wait(120) and handle.result == "ok"
            assert sidecar.ruleset_epoch >= 1

            for i in range(n, 2 * n):
                assert enq(i, "beta") is not None
            got_b = poll_all(n)
            stats = sidecar.stats()
            sidecar.stop()
            worker.join(30)

            assert sorted(got_a) == list(range(n))
            assert sorted(got_b) == list(range(n, 2 * n))
            for got in (got_a, got_b):
                assert all(len(a) == 1 for a in got.values())
            # Each phase bit-exact under ITS plan epoch.
            for i in range(n):
                assert got_a[i][0] & 3 == (1 if i % 3 == 0 else 0), i
            for i in range(n, 2 * n):
                assert got_b[i][0] & 3 == (1 if i % 3 == 0 else 0), i
            # Megastep windows ran on both sides of the flip, and no
            # slice ever computed under a different epoch than it was
            # staged with: the flip happened at a window boundary.
            assert stats["megastep"]["windows"] > 0
            assert stats["megastep"]["echo_mismatch"] == 0
        finally:
            sidecar.stop()
            ring.close()


class TestMegastepLintRegistry:
    """ISSUE 12 satellite: the megastep hot path is registered, with a
    mutation proof that a fresh allocation in the window stage/dispatch
    path fails `make analyze`."""

    def test_megastep_functions_registered(self):
        from tools.analyze import lint_config

        for fn in (
            "pingoo_tpu/engine/batch.py::DeviceInputQueue.fill_slice",
            "pingoo_tpu/engine/batch.py::DeviceInputQueue.device_stack",
            "pingoo_tpu/engine/verdict.py::finish_megastep",
            "pingoo_tpu/engine/service.py::"
            "VerdictService._evaluate_megastep",
            "pingoo_tpu/sched/scheduler.py::CostModel.observe_megastep",
            "pingoo_tpu/sched/scheduler.py::CostModel.estimate_megastep",
            "pingoo_tpu/obs/pipeline.py::PipelineStats.note_megastep",
        ):
            assert fn in lint_config.HOT_FUNCTIONS, fn
        for fn in (
            "pingoo_tpu/engine/verdict.py::make_megastep_fn.slice_step",
            "pingoo_tpu/engine/verdict.py::make_megastep_fn.megastep",
        ):
            assert fn in lint_config.TRACED_FUNCTIONS, fn

    def test_mutated_megastep_alloc_fails_lint(self):
        """The window fill copies into REUSED queue stacks; a fresh
        allocation inside _evaluate_megastep must fail the lint."""
        from tools.analyze import REPO_ROOT, lint

        with open(os.path.join(REPO_ROOT, "pingoo_tpu", "engine",
                               "service.py")) as f:
            src = f.read()
        needle = "            buf = self._mega_queue.checkout()"
        assert needle in src
        mutated = src.replace(
            needle,
            "            scratch = np.zeros((64, 64))\n" + needle, 1)
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/engine/service.py")
        assert any(f.rule == "hot-alloc" for f in findings), findings
