"""Live serving mesh + continuous-batching scheduler (ISSUE 6).

Tier promotion of the MULTICHIP dryrun: the dp×tp×sp mesh used to be
exercised only by `__graft_entry__.dryrun_multichip` (offline, no
requests). Here it SERVES — VerdictService boots with PINGOO_MESH on
the 8-virtual-device CPU backend (conftest forces
`--xla_force_host_platform_device_count=8`) and live-served verdicts
are compared bit-for-bit against the single-device path across dp/tp/sp
combos. The standalone reproduction (a fresh process with the XLA flag,
as `make mesh-smoke` runs it) is the @slow subprocess test.

Also here: the scheduler unit surface (EWMA cost model, launch policy,
env config), the burst test showing deadline-miss counters move under
an artificially tight PINGOO_DEADLINE_MS, the fail-open policies, and
the batch-assembly fairness fix (per-request stamping).
"""

import asyncio
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.engine.batch import RequestTuple, pow2_batch_size
from pingoo_tpu.engine.service import VerdictService
from pingoo_tpu.sched import (CostModel, MeshExecutor, Scheduler,
                              SchedulerConfig, seed_from_bench_history)
from pingoo_tpu.parallel import parse_mesh_spec

from test_parity import LISTS, RULE_SOURCES, make_rules, random_requests

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- scheduler core (pure unit surface) --------------------------------------


class TestSchedulerConfig:
    def test_env_parsing(self, monkeypatch):
        monkeypatch.setenv("PINGOO_SCHED_MODE", "fixed")
        monkeypatch.setenv("PINGOO_DEADLINE_MS", "7.5")
        monkeypatch.setenv("PINGOO_SCHED_FAILOPEN", "allow")
        cfg = SchedulerConfig.from_env(max_batch=256)
        assert cfg.mode == "fixed"
        assert cfg.deadline_ms == 7.5
        assert cfg.failopen == "allow"
        assert cfg.max_batch == 256

    def test_bad_env_falls_back_to_defaults(self, monkeypatch):
        monkeypatch.setenv("PINGOO_SCHED_MODE", "warp-speed")
        monkeypatch.setenv("PINGOO_DEADLINE_MS", "soon")
        monkeypatch.setenv("PINGOO_SCHED_FAILOPEN", "explode")
        cfg = SchedulerConfig.from_env(max_batch=64)
        assert cfg.mode == "continuous"
        assert cfg.deadline_ms == 2.0  # the p99 north-star budget
        assert cfg.failopen == "serve"

    def test_mesh_spec_parsing(self):
        assert parse_mesh_spec("2x2x2") == (2, 2, 2)
        assert parse_mesh_spec("8X1x1") == (8, 1, 1)
        for bad in ("", "2x2", "2x2x2x2", "axbxc", "0x1x1", "-1x1x1"):
            with pytest.raises(ValueError):
                parse_mesh_spec(bad)


class TestCostModel:
    def test_ewma_converges_to_observations(self):
        cm = CostModel(max_batch=1024, seed_ms=10.0, alpha=0.5)
        for _ in range(20):
            cm.observe(512, 3.0)
        assert abs(cm.estimate(512) - 3.0) < 0.1
        # Other buckets keep the affine seed until observed.
        assert cm.estimate(8) == pytest.approx(10.0 * (0.5 + 0.5 * 8 / 1024))

    def test_first_observation_replaces_seed(self):
        cm = CostModel(max_batch=256, seed_ms=100.0)
        cm.observe(256, 2.0)
        assert cm.estimate(256) == 2.0

    def test_seed_scales_with_batch_size(self):
        cm = CostModel(max_batch=2048, seed_ms=2.0)
        assert cm.estimate(2048) > cm.estimate(64) > 0

    def test_seed_from_bench_history(self, tmp_path):
        hist = tmp_path / "hist.jsonl"
        hist.write_text(
            '{"ts": 1, "p_batch_ms": 9.9}\n'
            "not json\n"
            '{"ts": 2, "p_batch_ms": 1.41}\n'
            '{"ts": 3, "value": 0}\n')
        # Newest USABLE entry wins (the ts=3 line has no p_batch_ms).
        assert seed_from_bench_history(str(hist)) == 1.41
        assert seed_from_bench_history(str(tmp_path / "missing")) is None

    def test_env_seed_wins(self, monkeypatch):
        monkeypatch.setenv("PINGOO_SCHED_SEED_MS", "4.25")
        assert CostModel(max_batch=64).seed_ms == 4.25


class TestLaunchPolicy:
    def _sched(self, **kw):
        cfg = SchedulerConfig(max_batch=kw.pop("max_batch", 128),
                              deadline_ms=kw.pop("deadline_ms", 2.0))
        s = Scheduler(cfg, plane="python")
        s.cost = CostModel(max_batch=cfg.max_batch,
                           seed_ms=kw.pop("seed_ms", 1.0))
        return s

    def test_launches_when_full(self):
        s = self._sched()
        assert s.should_launch(128, time.monotonic(), time.monotonic())

    def test_waits_while_slack_covers_estimate(self):
        s = self._sched(deadline_ms=50.0, seed_ms=1.0)
        now = time.monotonic()
        assert not s.should_launch(4, now, now)
        assert s.wait_budget_s(4, now, now) > 0.04

    def test_launches_when_slack_exhausted(self):
        s = self._sched(deadline_ms=2.0, seed_ms=1.0)
        now = time.monotonic()
        # The oldest request admitted 1.5 ms ago: 0.5 ms slack < 1 ms
        # estimated dispatch -> launch now.
        assert s.should_launch(4, now - 0.0015, now)

    def test_unmeetable(self):
        s = self._sched(deadline_ms=2.0, seed_ms=1.0)
        now = time.monotonic()
        assert s.unmeetable(now - 0.0025, now, 4)  # already past budget
        assert not s.unmeetable(now, now, 4)

    def test_miss_and_failopen_accounting(self):
        s = self._sched(deadline_ms=2.0)
        before = s.metrics.deadline_miss.value
        assert s.note_resolved(0.0, 1.0)  # 1000 ms >> 2 ms
        assert not s.note_resolved(0.0, 0.0001)
        assert s.metrics.deadline_miss.value == before + 1
        s.note_misses(3)
        assert s.metrics.deadline_miss.value == before + 4
        fo = s.metrics.failopen.value
        s.note_failopen(2)
        assert s.metrics.failopen.value == fo + 2
        snap = s.snapshot()
        assert snap["deadline_misses"] >= 4 and snap["failopens"] >= 2


class TestBatchAlignment:
    def test_pow2_ladder_unchanged_single_device(self):
        assert pow2_batch_size(1, 1024) == 8
        assert pow2_batch_size(9, 1024) == 16
        assert pow2_batch_size(2000, 1024) == 2000  # never below n
        assert pow2_batch_size(1000, 1024) == 1024

    def test_dp_alignment(self):
        assert pow2_batch_size(9, 1024, multiple=2) == 16
        assert pow2_batch_size(9, 1024, multiple=3) == 18
        assert pow2_batch_size(16, 1024, multiple=8) == 16


# -- live mesh serving (8 virtual CPU devices from conftest) ------------------


def _drive(loop_runner, svc, reqs):
    async def flow():
        await svc.start()
        try:
            return await asyncio.gather(*[svc.evaluate(r) for r in reqs])
        finally:
            await svc.stop()

    return loop_runner.run(flow(), timeout=300)


def _requests(n=48, seed=1234):
    rng = random.Random(seed)
    reqs = random_requests(rng, n)
    for i, r in enumerate(reqs):
        r.trace_id = f"mesh-{seed}-{i}"
    return reqs


class TestMeshServing:
    def _serve(self, loop_runner, monkeypatch, mesh, reqs, sample=None):
        if mesh is None:
            monkeypatch.delenv("PINGOO_MESH", raising=False)
        else:
            monkeypatch.setenv("PINGOO_MESH", mesh)
        if sample is not None:
            monkeypatch.setenv("PINGOO_PARITY_SAMPLE", sample)
        plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
        svc = VerdictService(plan, LISTS, use_device=True, max_batch=64)
        verdicts = _drive(loop_runner, svc, reqs)
        return svc, verdicts

    @pytest.mark.parametrize("mesh", ["2x1x1", "1x2x1", "2x2x2"])
    def test_mesh_served_verdicts_bit_identical(self, loop_runner,
                                                monkeypatch, mesh):
        """ISSUE 6 acceptance: live-served verdicts through the dp/tp/sp
        mesh are bit-identical to the single-device path."""
        reqs = _requests()
        ref_svc, want = self._serve(loop_runner, monkeypatch, None, reqs)
        assert ref_svc.mesh is not None and not ref_svc.mesh.active
        svc, got = self._serve(loop_runner, monkeypatch, mesh, reqs)
        dp, tp, sp = parse_mesh_spec(mesh)
        assert svc.mesh.active and svc.mesh.devices == dp * tp * sp
        assert svc.sched.metrics.mesh_devices.value == dp * tp * sp
        assert not any(v.degraded for v in want + got)
        for i, (w, g) in enumerate(zip(want, got)):
            assert w.action == g.action, (mesh, i)
            assert w.verified_block == g.verified_block, (mesh, i)
            np.testing.assert_array_equal(w.matched, g.matched,
                                          err_msg=f"{mesh} row {i}")

    def test_mesh_serving_under_parity_audit(self, loop_runner,
                                             monkeypatch):
        """The shadow-parity auditor runs unchanged over mesh-served
        batches: dp/tp sharding is continuously parity-checked (the
        acceptance criterion's mismatch-counters-stay-0)."""
        svc, verdicts = self._serve(loop_runner, monkeypatch, "2x2x2",
                                    _requests(32, seed=77), sample="1")
        assert svc.parity is not None
        assert svc.parity.flush(30)
        assert svc.parity.checked_total.value > 0
        assert svc.parity.mismatch_total.value == 0
        assert not any(v.degraded for v in verdicts)

    def test_mesh_unavailable_degrades_to_single_device(
            self, loop_runner, monkeypatch):
        """A spec needing more devices than the backend has must serve
        single-device (fail-open posture), not crash the plane."""
        svc, verdicts = self._serve(loop_runner, monkeypatch, "64x1x1",
                                    _requests(8, seed=5))
        assert not svc.mesh.active
        assert svc.sched.metrics.mesh_devices.value == 1
        assert not any(v.degraded for v in verdicts)


class TestContinuousScheduler:
    def _plan(self):
        return compile_ruleset(make_rules(RULE_SOURCES[:8]), LISTS)

    def test_deadline_miss_counters_move_under_tight_deadline(
            self, loop_runner, monkeypatch):
        """ISSUE 6 satellite: a burst under an artificially tight
        PINGOO_DEADLINE_MS moves the miss counters (the CPU backend
        cannot verdict a batch in 1 microsecond)."""
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        monkeypatch.setenv("PINGOO_DEADLINE_MS", "0.001")
        monkeypatch.setenv("PINGOO_SCHED_MODE", "continuous")
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64)
        before = svc.sched.metrics.deadline_miss.value
        verdicts = _drive(loop_runner, svc, _requests(48, seed=9))
        assert len(verdicts) == 48
        assert svc.sched.deadline_misses > 0
        assert svc.sched.metrics.deadline_miss.value > before
        assert svc.sched.launches > 0
        assert svc.sched.metrics.batch_size.count > 0

    def test_failopen_allow_policy(self, loop_runner, monkeypatch):
        """An unmeetable deadline with PINGOO_SCHED_FAILOPEN=allow
        resolves requests immediately with the degraded fail-open
        verdict instead of occupying device budget."""
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        monkeypatch.setenv("PINGOO_DEADLINE_MS", "0.001")
        monkeypatch.setenv("PINGOO_SCHED_FAILOPEN", "allow")
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64)
        verdicts = _drive(loop_runner, svc, _requests(24, seed=11))
        assert svc.sched.failopens > 0
        assert any(v.degraded and v.action == 0 for v in verdicts)

    def test_failopen_interpret_policy_serves_real_verdicts(
            self, loop_runner, monkeypatch):
        """`interpret` fails open to the HOST interpreter: late
        requests still get real (bit-exact) verdicts, off the device
        path."""
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        monkeypatch.setenv("PINGOO_DEADLINE_MS", "0.001")
        monkeypatch.setenv("PINGOO_SCHED_FAILOPEN", "interpret")
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64)
        reqs = [RequestTuple(path="/.env", user_agent="curl"),
                RequestTuple(path="/clean", user_agent="Mozilla/5.0")]
        verdicts = _drive(loop_runner, svc, reqs)
        if svc.sched.failopens:  # the tight deadline fired
            assert verdicts[0].action == 1  # /.env still blocks
            assert verdicts[1].action == 0

    def test_fixed_mode_keeps_legacy_window(self, loop_runner,
                                            monkeypatch):
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        monkeypatch.setenv("PINGOO_SCHED_MODE", "fixed")
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64, max_wait_us=100)
        assert svc.sched.config.mode == "fixed"
        verdicts = _drive(loop_runner, svc, _requests(16, seed=3))
        assert len(verdicts) == 16

    def test_batch_assembly_stamped_per_request(self, loop_runner,
                                                monkeypatch):
        """ISSUE 6 fairness satellite: batch_assembly observes once PER
        REQUEST from its own admit timestamp (the old code observed
        once per batch from the first pop, under-reporting late
        admits)."""
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64)
        h = svc.stats.stage_hist["batch_assembly"]
        before = h.count
        n = 24
        verdicts = _drive(loop_runner, svc, _requests(n, seed=21))
        assert len(verdicts) == n
        # One observation per request (+ the warmup request), NOT one
        # per batch: strictly more observations than batches ran.
        assert h.count - before >= n
        assert svc.stats.batches < n

    def test_flight_recorder_rows_carry_admit_to_launch(
            self, loop_runner, monkeypatch):
        monkeypatch.delenv("PINGOO_MESH", raising=False)
        svc = VerdictService(self._plan(), LISTS, use_device=True,
                             max_batch=64)
        assert svc.flight_recorder is not None
        _drive(loop_runner, svc, _requests(8, seed=31))
        entries = svc.flight_recorder.snapshot()
        assert entries
        assert all("admit_to_launch_ms" in e["stages_ms"]
                   for e in entries if e["trace_id"].startswith("mesh-"))


# -- lint mutation proofs -----------------------------------------------------


class TestSchedLintMutations:
    """ISSUE 6 satellite: the admission loop and EWMA update are
    registered hot (tools/analyze/lint_config.py) — prove the linter
    actually fires when a host sync or allocation creeps in."""

    def _source(self, rel="pingoo_tpu/sched/scheduler.py"):
        with open(os.path.join(REPO, rel)) as f:
            return f.read()

    def test_sched_registered_in_lint_config(self):
        from tools.analyze import lint, lint_config as cfg

        assert "pingoo_tpu/sched" in cfg.LINT_DIRS
        for fn in ("pingoo_tpu/sched/scheduler.py::CostModel.observe",
                   "pingoo_tpu/sched/scheduler.py::Scheduler.note_launch",
                   "pingoo_tpu/sched/mesh_exec.py::MeshExecutor"
                   ".shard_batch"):
            assert fn in cfg.HOT_FUNCTIONS, fn
        findings, warnings = lint.lint_paths()
        assert findings == [], "\n".join(str(f) for f in findings)
        assert warnings == [], "\n".join(warnings)

    def test_sync_in_ewma_update_fails_lint(self):
        """A device materialization inserted into CostModel.observe
        (the hot EWMA update) must fail the hot-path lint."""
        from tools.analyze import lint

        src = self._source()
        marker = "        bucket = _pow2_bucket(max(1, batch_size), self.max_batch)\n        prev = self._ewma.get(bucket)"
        assert marker in src
        mutated = src.replace(
            marker,
            "        ms = float(np.asarray(ms))\n" + marker, 1)
        findings, _ = lint.lint_source(
            mutated, "pingoo_tpu/sched/scheduler.py")
        assert any(f.rule == "sync-asarray-hot"
                   and "observe" in f.message for f in findings)

    def test_alloc_in_launch_policy_fails_lint(self):
        """A fresh numpy allocation in the per-batch launch accounting
        must fail the hot-path lint (no arrays between dispatch and
        resolve)."""
        from tools.analyze import lint

        src = self._source()
        marker = "        self.launches += 1"
        assert marker in src
        mutated = src.replace(
            marker, marker + "\n        scratch = np.zeros(64)", 1)
        findings, _ = lint.lint_source(
            mutated, "pingoo_tpu/sched/scheduler.py")
        assert any(f.rule == "hot-alloc"
                   and "note_launch" in f.message for f in findings)

    def test_sync_in_mesh_shard_batch_fails_lint(self):
        """shard_batch may only ISSUE placements (device_put is async);
        materializing an array there is a host sync between dispatch
        and resolve and must fail the lint."""
        from tools.analyze import lint

        src = self._source("pingoo_tpu/sched/mesh_exec.py")
        marker = "        sig = tuple(sorted(arrays))"
        assert marker in src
        mutated = src.replace(
            marker,
            "        import numpy as np\n"
            "        first = np.asarray(next(iter(arrays.values())))\n"
            + marker, 1)
        findings, _ = lint.lint_source(
            mutated, "pingoo_tpu/sched/mesh_exec.py")
        assert any(f.rule == "sync-asarray-hot"
                   and "shard_batch" in f.message for f in findings)


# -- subprocess reproduction (tier-2: fresh process, explicit XLA flag) ------

_CHILD_SCRIPT = r"""
import asyncio, os, random, sys

import numpy as np

sys.path.insert(0, {repo!r})
sys.path.insert(0, os.path.join({repo!r}, "tests"))

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.engine.service import VerdictService
from test_parity import LISTS, RULE_SOURCES, make_rules, random_requests


def serve(mesh, reqs, deadline_ms=None):
    os.environ["PINGOO_MESH"] = mesh
    if deadline_ms is not None:
        os.environ["PINGOO_DEADLINE_MS"] = deadline_ms
    plan = compile_ruleset(make_rules(RULE_SOURCES), LISTS)
    svc = VerdictService(plan, LISTS, use_device=True, max_batch=64)

    async def flow():
        await svc.start()
        try:
            return await asyncio.gather(*[svc.evaluate(r) for r in reqs])
        finally:
            await svc.stop()

    return svc, asyncio.run(flow())


reqs = random_requests(random.Random(424), 48)
svc1, want = serve("1x1x1", reqs)
assert not svc1.mesh.active
svc2, got = serve("2x2x2", reqs)
assert svc2.mesh.active and svc2.mesh.devices == 8
for w, g in zip(want, got):
    assert w.action == g.action
    np.testing.assert_array_equal(w.matched, g.matched)
# Burst under a 1 us deadline: miss counters must move.
svc3, _ = serve("2x2x2", reqs, deadline_ms="0.001")
assert svc3.sched.deadline_misses > 0, "tight deadline produced no misses"
print("MESH_SERVING_OK", svc3.sched.deadline_misses)
"""


@pytest.mark.slow
class TestSubprocessMeshServing:
    def test_eight_fake_device_serving(self):
        """The standalone reproduction (`make mesh-smoke` shape): a
        fresh process forcing 8 virtual CPU devices via XLA_FLAGS
        serves through PINGOO_MESH=2x2x2 bit-identically and shows
        deadline misses under a tight budget."""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        env.pop("PINGOO_MESH", None)
        env.pop("PINGOO_DEADLINE_MS", None)
        env.pop("PINGOO_SCHED_MODE", None)
        env.pop("PINGOO_SCHED_FAILOPEN", None)
        proc = subprocess.run(
            [sys.executable, "-c", _CHILD_SCRIPT.format(repo=REPO)],
            capture_output=True, text=True, timeout=900, env=env,
            cwd=REPO)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "MESH_SERVING_OK" in proc.stdout, proc.stdout[-500:]
