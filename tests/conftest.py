"""Test configuration.

Tests run on the CPU backend with 8 virtual devices so sharding/mesh code
paths (dp x tp x sp) are exercised without TPU hardware, per SURVEY.md §4
item (4). Must run before the first `import jax` anywhere in the test
process.
"""

import os

# Force CPU unconditionally: the ambient environment routes jax to a
# tunneled TPU ('axon' platform, registered by sitecustomize), which
# would make every test pay network round-trips. The env var alone is
# overridden by the plugin, so also update jax.config before any
# backend initialization. Benchmarks opt into the real device.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# -- async test harness ------------------------------------------------------
# pytest-asyncio isn't available in this image; host-plane integration
# tests instead run against a shared event loop in a background thread.

import asyncio  # noqa: E402
import threading  # noqa: E402

import pytest  # noqa: E402


class LoopRunner:
    """Run coroutines on a dedicated background event loop."""

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def run(self, coro, timeout=60):
        return asyncio.run_coroutine_threadsafe(
            coro, self.loop).result(timeout)

    def close(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=5)


@pytest.fixture(scope="session")
def loop_runner():
    runner = LoopRunner()
    yield runner
    runner.close()


def pytest_configure(config):
    """Build ALL native binaries up front when a toolchain exists: many
    tests exec `httpd`/`drain`/`loadgen*`/`pong` directly (they are
    build outputs, not committed), and a fresh tree would otherwise
    fail on the first direct spawn rather than the build."""
    import subprocess

    try:
        from pingoo_tpu import native_ring

        subprocess.run(["make", "-C", native_ring.NATIVE_DIR, "all"],
                       check=True, capture_output=True, timeout=300)
    except Exception:
        # Never abort the session from this convenience hook: per-test
        # skips/spawn errors will say what's missing.
        pass
