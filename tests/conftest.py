"""Test configuration.

Tests run on the CPU backend with 8 virtual devices so sharding/mesh code
paths (dp x tp x sp) are exercised without TPU hardware, per SURVEY.md §4
item (4). Must run before the first `import jax` anywhere in the test
process.
"""

import os

# Force CPU unconditionally: the ambient environment routes jax to a
# tunneled TPU ('axon' platform, registered by sitecustomize), which
# would make every test pay network round-trips. The env var alone is
# overridden by the plugin, so also update jax.config before any
# backend initialization. Benchmarks opt into the real device.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
