"""Unit tests: JWT/JOSE, TLS manager, captcha manager, discovery,
verdict service fallback."""

import asyncio
import json
import ssl
import time

import pytest

from pingoo_tpu.host import jwt as jose
from pingoo_tpu.host.captcha import CaptchaManager, generate_captcha_client_id
from pingoo_tpu.host.tlsmgr import TlsManager, cert_sans, generate_self_signed


class TestJose:
    @pytest.mark.parametrize("alg", [jose.ALG_HS512, jose.ALG_EDDSA,
                                     jose.ALG_ES256, jose.ALG_ES512])
    def test_sign_verify_roundtrip(self, alg):
        key = jose.Key.generate(alg, kid="k1")
        now = int(time.time())
        token = jose.sign(key, {"sub": "x", "exp": now + 60, "iss": "pingoo"})
        claims = jose.parse_and_verify(token, key, issuer="pingoo")
        assert claims["sub"] == "x"

    def test_tampered_signature_rejected(self):
        key = jose.Key.generate(jose.ALG_EDDSA)
        token = jose.sign(key, {"sub": "x"})
        head, payload, sig = token.split(".")
        bad = head + "." + payload + "." + sig[:-4] + "AAAA"
        with pytest.raises(jose.JwtError, match="signature"):
            jose.parse_and_verify(bad, key)

    def test_tampered_claims_rejected(self):
        key = jose.Key.generate(jose.ALG_EDDSA)
        token = jose.sign(key, {"admin": False})
        head, _, sig = token.split(".")
        forged_claims = jose.b64url_encode(json.dumps({"admin": True}).encode())
        with pytest.raises(jose.JwtError):
            jose.parse_and_verify(head + "." + forged_claims + "." + sig, key)

    def test_expiry_and_nbf(self):
        key = jose.Key.generate(jose.ALG_HS512)
        now = time.time()
        token = jose.sign(key, {"exp": int(now - 3600)})
        with pytest.raises(jose.JwtError, match="expired"):
            jose.parse_and_verify(token, key)
        # within drift tolerance -> accepted (jwt.rs drift checks)
        token = jose.sign(key, {"exp": int(now - 10)})
        jose.parse_and_verify(token, key, drift_tolerance_s=60)
        token = jose.sign(key, {"nbf": int(now + 3600)})
        with pytest.raises(jose.JwtError, match="not yet valid"):
            jose.parse_and_verify(token, key)

    def test_audience_issuer(self):
        key = jose.Key.generate(jose.ALG_HS512)
        token = jose.sign(key, {"aud": ["a", "b"], "iss": "me"})
        jose.parse_and_verify(token, key, audience="a", issuer="me")
        with pytest.raises(jose.JwtError, match="audience"):
            jose.parse_and_verify(token, key, audience="c")
        with pytest.raises(jose.JwtError, match="issuer"):
            jose.parse_and_verify(token, key, issuer="you")

    def test_alg_confusion_rejected(self):
        """Token signed HS512 must not verify against an Ed25519 key."""
        hs = jose.Key.generate(jose.ALG_HS512)
        ed = jose.Key.generate(jose.ALG_EDDSA)
        token = jose.sign(hs, {"sub": "x"})
        with pytest.raises(jose.JwtError, match="algorithm mismatch"):
            jose.parse_and_verify(token, ed)

    @pytest.mark.parametrize("alg", [jose.ALG_EDDSA, jose.ALG_ES256,
                                     jose.ALG_ES512, jose.ALG_HS512])
    def test_jwk_roundtrip(self, alg):
        key = jose.Key.generate(alg, kid="kid9")
        jwks_json = jose.Jwks(keys=[key]).to_json(include_private=True)
        restored = jose.Jwks.from_json(jwks_json).find("kid9")
        token = jose.sign(key, {"sub": "x"})
        assert jose.parse_and_verify(token, restored)["sub"] == "x"
        # public-only JWKS still verifies (asymmetric algs)
        if alg != jose.ALG_HS512:
            pub = jose.Jwks.from_json(
                jose.Jwks(keys=[key]).to_json()).find("kid9")
            assert jose.parse_and_verify(token, pub)["sub"] == "x"


class TestTlsManager:
    def test_self_signed_and_sni(self, tmp_path):
        mgr = TlsManager(str(tmp_path / "tls"))
        # Default '*' cert generated on first boot (tls_manager.rs:193-231).
        assert (tmp_path / "tls" / "default.pingoo.pem").exists()
        assert mgr.resolve("anything.example") is not None

        cert, key = generate_self_signed(["example.com", "*.api.example.com"])
        (tmp_path / "tls" / "example.pem").write_bytes(cert)
        (tmp_path / "tls" / "example.key").write_bytes(key)
        mgr2 = TlsManager(str(tmp_path / "tls"))
        exact = mgr2.resolve("example.com")
        wild = mgr2.resolve("v1.api.example.com")
        default = mgr2.resolve("other.test")
        assert exact is not None and wild is not None and default is not None
        assert exact is not default and wild is not default

    def test_cert_sans(self):
        cert, _ = generate_self_signed(["a.test", "*.b.test"])
        assert set(cert_sans(cert)) == {"a.test", "*.b.test"}

    def test_tls13_only(self, tmp_path):
        mgr = TlsManager(str(tmp_path / "tls"))
        ctx = mgr.server_context()
        assert ctx.minimum_version == ssl.TLSVersion.TLSv1_3


class TestCaptchaManager:
    def test_pow_flow(self, tmp_path):
        mgr = CaptchaManager(str(tmp_path / "jwks.json"))
        client_id = generate_captcha_client_id("1.2.3.4", "UA", "host")
        body, cookie = mgr.init_challenge(client_id)
        token = cookie.split("=", 1)[1].split(";")[0]
        import hashlib

        nonce = 0
        while True:
            digest = hashlib.sha256(
                (body["challenge"] + str(nonce)).encode()).hexdigest()
            if digest.startswith("0" * body["difficulty"]):
                break
            nonce += 1
        ok, verified_cookie = mgr.verify_challenge(
            {"nonce": str(nonce), "hash": digest}, token, client_id)
        assert ok and verified_cookie
        verified_token = verified_cookie.split("=", 1)[1].split(";")[0]
        assert mgr.is_verified(verified_token, client_id)
        # A different client id must not validate (constant-time compare).
        other = generate_captcha_client_id("5.6.7.8", "UA", "host")
        assert not mgr.is_verified(verified_token, other)

    def test_wrong_pow_rejected(self, tmp_path):
        mgr = CaptchaManager(str(tmp_path / "jwks.json"))
        client_id = generate_captcha_client_id("1.2.3.4", "UA", "host")
        _, cookie = mgr.init_challenge(client_id)
        token = cookie.split("=", 1)[1].split(";")[0]
        ok, _ = mgr.verify_challenge(
            {"nonce": "1", "hash": "f" * 64}, token, client_id)
        assert not ok

    def test_key_persistence(self, tmp_path):
        path = str(tmp_path / "jwks.json")
        mgr1 = CaptchaManager(path)
        client_id = generate_captcha_client_id("1.2.3.4", "UA", "host")
        _, cookie = mgr1.init_challenge(client_id)
        # A new manager instance reuses the persisted key (captcha.rs:78-123).
        mgr2 = CaptchaManager(path)
        token = cookie.split("=", 1)[1].split(";")[0]
        from pingoo_tpu.host import jwt as j

        claims = j.parse_and_verify(token, mgr2.key, issuer="pingoo",
                                    drift_tolerance_s=5)
        assert claims["client_id"] == client_id


class TestDiscovery:
    def test_static_and_dns(self, loop_runner):
        from pingoo_tpu.config import parse_config
        from pingoo_tpu.host.discovery import ServiceRegistry

        config = parse_config({
            "listeners": {"l": {"address": "http://0.0.0.0:8080"}},
            "services": {
                "s": {"http_proxy": ["http://127.0.0.1:9000",
                                      "http://localhost:9001"]},
            },
        })
        registry = ServiceRegistry(config.services, enable_docker=False,
                                   enable_dns=True)
        loop_runner.run(registry.discover())
        ups = registry.get_upstreams("s")
        assert {(u.ip, u.port) for u in ups} >= {("127.0.0.1", 9000),
                                                ("127.0.0.1", 9001)}
        assert registry.get_upstreams("unknown") == []


class TestHostParsing:
    def test_ipv6_host_header(self):
        from pingoo_tpu.host.httpd import Request, get_host

        req = Request(method="GET", target="/", path="/",
                      headers=[("host", "[::1]:8080")])
        assert get_host(req) == "[::1]"
        req = Request(method="GET", target="/", path="/",
                      headers=[("host", "example.com:443")])
        assert get_host(req) == "example.com"
        req = Request(method="GET", target="http://[2001:db8::1]:80/x",
                      path="/x", headers=[])
        assert get_host(req) == "[2001:db8::1]"

    def test_overlong_host_becomes_empty(self):
        """Reference get_host: heapless from_str overflow -> EMPTY, not
        truncated (http_listener.rs:287,292)."""
        from pingoo_tpu.host.httpd import Request, get_host

        long_host = "a" * 300 + ".example.com"
        req = Request(method="GET", target="/", path="/",
                      headers=[("host", long_host)])
        assert get_host(req) == ""
        ok = "b" * 256  # exactly at the cap still fits
        req = Request(method="GET", target="/", path="/",
                      headers=[("host", ok)])
        assert get_host(req) == ok


class TestRingCapacityValidation:
    def test_non_pow2_rejected(self, tmp_path):
        from pingoo_tpu import native_ring

        if not native_ring.ensure_built():
            pytest.skip("no native toolchain")
        with pytest.raises(ValueError, match="power of two"):
            native_ring.Ring(str(tmp_path / "r"), capacity=1000, create=True)


class TestBackendProbe:
    """ensure_jax_backend must degrade a dead/wedged accelerator to CPU
    without hanging: a wedged device tunnel makes backend init BLOCK
    (not raise), so the probe runs out-of-process under a deadline
    (found live: a stale device claim hung `jax.devices()` forever and
    the server never bound its listeners)."""

    def test_bogus_accelerator_degrades_to_working_backend(self):
        import subprocess
        import sys

        # Separate interpreter: the probe mutates global jax config.
        # The probe must land on SOME working backend: CPU on plain
        # hosts, or a real accelerator when one is attached (degrading
        # past a bogus platform name to a live TPU is correct, so the
        # assertion accepts any platform that initializes and computes).
        code = (
            "import os; os.environ['JAX_PLATFORMS']='nonexistent_accel';\n"
            "from pingoo_tpu.engine.service import ensure_jax_backend\n"
            "ok = ensure_jax_backend(probe_timeout_s=30)\n"
            "import jax, jax.numpy as jnp\n"
            "assert ok, 'backend probe failed entirely'\n"
            "assert len(jax.devices()) >= 1, 'no devices after probe'\n"
            "assert int(jnp.arange(4).sum()) == 6\n"
            "print('DEGRADED_OK', jax.devices()[0].platform)\n"
        )
        proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "DEGRADED_OK" in proc.stdout

    def test_hung_probe_times_out_to_cpu(self):
        """A probe subprocess that hangs (simulated via a sitecustomize
        that sleeps on import) must hit the deadline and pin CPU."""
        import os
        import subprocess
        import sys
        import tempfile
        import textwrap

        with tempfile.TemporaryDirectory() as td:
            # The inner probe subprocess inherits PYTHONPATH; this
            # sitecustomize hangs ONLY the probe child (guarded by env),
            # simulating a wedged tunnel claim.
            with open(os.path.join(td, "sitecustomize.py"), "w") as f:
                f.write(textwrap.dedent("""
                    import os, time
                    if os.environ.get("PROBE_CHILD_HANGS") and \\
                            "jax.devices" in " ".join(
                                __import__("sys").argv):
                        time.sleep(3600)
                """))
            code = (
                "import os\n"
                "os.environ['JAX_PLATFORMS']='fake_tpu'\n"
                "os.environ['PROBE_CHILD_HANGS']='1'\n"
                "from pingoo_tpu.engine.service import ensure_jax_backend\n"
                "ok = ensure_jax_backend(probe_timeout_s=5)\n"
                "import jax\n"
                "assert ok\n"
                "assert jax.devices()[0].platform == 'cpu'\n"
                "print('TIMEOUT_DEGRADED_OK')\n"
            )
            env = dict(os.environ)
            env["PYTHONPATH"] = td + os.pathsep + env.get("PYTHONPATH", "")
            proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                                  capture_output=True, text=True, env=env)
            assert proc.returncode == 0, proc.stderr[-2000:]
            assert "TIMEOUT_DEGRADED_OK" in proc.stdout


class TestProfilerHook:
    def test_profile_dir_captures_trace(self, loop_runner, tmp_path,
                                        monkeypatch):
        """PINGOO_PROFILE_DIR wraps the serving window in a
        jax.profiler trace (SURVEY §5 tracing/profiling)."""
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression

        monkeypatch.setenv("PINGOO_PROFILE_DIR", str(tmp_path))
        rules = [RuleConfig(
            name="r", actions=(Action.BLOCK,),
            expression=compile_expression('http_request.path == "/x"'))]
        plan = compile_ruleset(rules, {})
        svc = VerdictService(plan, {}, use_device=True, max_wait_us=100)

        async def flow():
            await svc.start()
            try:
                return await svc.evaluate(RequestTuple(path="/x"))
            finally:
                await svc.stop()

        v = loop_runner.run(flow())
        assert v.block
        # jax writes plugins/profile/<ts>/*.xplane.pb under the dir
        produced = list(tmp_path.rglob("*"))
        assert any(p.is_file() for p in produced), produced


class TestVerdictServiceFallback:
    def test_host_fallback_on_device_error(self, loop_runner):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="r", actions=(Action.BLOCK,),
            expression=compile_expression('http_request.path == "/x"'))]
        plan = compile_ruleset(rules, {})
        svc = VerdictService(plan, {}, use_device=True, max_wait_us=100)
        svc._verdict_fn = None  # simulate a dead device path

        async def flow():
            await svc.start()
            try:
                v1 = await svc.evaluate(RequestTuple(path="/x"))
                v2 = await svc.evaluate(RequestTuple(path="/y"))
                return v1, v2
            finally:
                await svc.stop()

        v1, v2 = loop_runner.run(flow())
        assert v1.block and not v2.block
        assert svc.stats.device_errors >= 1
        assert svc.stats.host_fallback_batches >= 1

    def test_collector_survives_total_failure(self, loop_runner):
        """Even if BOTH device and host paths explode, requests must
        resolve fail-open instead of hanging forever."""
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="r", actions=(Action.BLOCK,),
            expression=compile_expression("true"))]
        plan = compile_ruleset(rules, {})
        svc = VerdictService(plan, {}, use_device=False, max_wait_us=100)
        svc._evaluate_host = lambda batch: (_ for _ in ()).throw(
            RuntimeError("boom"))

        async def flow():
            await svc.start()
            try:
                import asyncio

                return await asyncio.wait_for(
                    svc.evaluate(RequestTuple(path="/x")), timeout=5), \
                    await asyncio.wait_for(
                        svc.evaluate(RequestTuple(path="/y")), timeout=5)
            finally:
                await svc.stop()

        v1, v2 = loop_runner.run(flow())
        assert v1.action == 0 and v2.action == 0  # fail-open, not hung


class TestOverflowRouting:
    """Fields past device capacity -> host interpreter over the FULL
    strings (reference matches full path/url; padding must not bypass)."""

    def _service(self, expr, use_device):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(name="r", actions=(Action.BLOCK,),
                            expression=compile_expression(expr))]
        plan = compile_ruleset(rules, {})
        return plan, VerdictService(plan, {}, use_device=use_device,
                                    max_wait_us=100)

    @pytest.mark.parametrize("use_device", [True, False])
    def test_padded_url_cannot_bypass_contains(self, use_device):
        from pingoo_tpu.engine.batch import RequestTuple

        plan, svc = self._service(
            'http_request.url.contains("attackmarker")', use_device)
        cap = plan.field_specs["url"]
        long_url = "/" + "A" * (cap + 100) + "attackmarker"
        matched = svc._evaluate_sync([
            RequestTuple(url=long_url, path="/x"),
            RequestTuple(url="/clean", path="/x"),
            RequestTuple(url="/attackmarker", path="/x"),
        ])
        assert matched[0, 0], "marker past device cap must still match"
        assert not matched[1, 0]
        assert matched[2, 0]

    def test_overflow_length_uses_full_string(self):
        from pingoo_tpu.engine.batch import RequestTuple

        plan, svc = self._service("length(http_request.path) > 3000", False)
        cap = plan.field_specs["path"]
        matched = svc._evaluate_sync([
            RequestTuple(path="/" + "p" * 3200),
            RequestTuple(path="/" + "p" * (cap - 10)),
        ])
        assert matched[0, 0]
        assert not matched[1, 0]

    def test_encode_marks_overflow_rows(self):
        from pingoo_tpu.engine.batch import RequestTuple, encode_requests

        batch = encode_requests([
            RequestTuple(url="/" + "x" * 5000),
            RequestTuple(url="/short"),
        ])
        assert batch.overflow.tolist() == [True, False]
        assert "overflow" not in batch.arrays  # never rides the pytree


class TestDiscoveryTtlAndWarnOnce:
    def _registry_with_dns_target(self):
        from pingoo_tpu.config.schema import ServiceConfig, Upstream
        from pingoo_tpu.host.discovery import ServiceRegistry

        svc = ServiceConfig(
            name="s", route=None,
            http_proxy=(Upstream(hostname="backend.test", port=9000,
                                 tls=False, ip=None),))
        return ServiceRegistry([svc], enable_docker=False, enable_dns=True)

    def test_dns_positive_min_ttl_suppresses_reresolve(self, loop_runner):
        """dns.rs positive_min_ttl=60s equivalent: a fresh answer is not
        re-resolved on every 2s tick."""
        reg = self._registry_with_dns_target()
        calls = {"n": 0}

        async def stub(hostname, port):
            calls["n"] += 1
            return [(2, 1, 6, "", ("10.0.0.5", port))]

        reg._getaddrinfo = stub
        for _ in range(5):
            loop_runner.run(reg.discover())
        assert calls["n"] == 1  # floor: one resolution within the window
        assert [u.ip for u in reg.get_upstreams("s")] == ["10.0.0.5"]

    def test_dns_failure_serves_last_known_within_negative_ttl(
            self, loop_runner):
        reg = self._registry_with_dns_target()
        state = {"fail": False}

        async def stub(hostname, port):
            if state["fail"]:
                raise OSError("resolver down")
            return [(2, 1, 6, "", ("10.0.0.7", port))]

        reg._getaddrinfo = stub
        loop_runner.run(reg.discover())
        # Age the cache past the positive floor, then fail the resolver.
        key = ("backend.test", 9000)
        ups, ts = reg._dns_cache[key]
        reg._dns_cache[key] = (ups, ts - 120)
        state["fail"] = True
        loop_runner.run(reg.discover())
        assert [u.ip for u in reg.get_upstreams("s")] == ["10.0.0.7"]
        # Past the negative cap the stale answer drops.
        reg._dns_cache[key] = (ups, ts - 4000)
        loop_runner.run(reg.discover())
        assert reg.get_upstreams("s") == []

    def test_docker_problem_container_warned_once(self, caplog):
        import logging

        from pingoo_tpu.host.discovery import ServiceRegistry

        reg = ServiceRegistry([], enable_docker=True, enable_dns=False)
        with caplog.at_level(logging.WARNING):
            for _ in range(3):
                reg._warn_container("abc123def456", "no usable port")
        warnings = [r for r in caplog.records
                    if "abc123def456"[:12] in r.getMessage()]
        assert len(warnings) == 1  # once per idle window, not per tick


class TestDockerDiscoveryEndToEnd:
    """Full Docker-discovery drive against a MOCK daemon on a real unix
    socket (reference docker/src/client.rs:41-145 + service_registry
    docker merge): labeled containers become upstreams; chunked
    transfer-encoding is de-framed; hot-swap applies on the next tick."""

    def _mock_daemon(self, tmp_path, payload_json):
        import socket as socketmod

        path = str(tmp_path / "docker.sock")
        srv = socketmod.socket(socketmod.AF_UNIX, socketmod.SOCK_STREAM)
        srv.bind(path)
        srv.listen(4)
        state = {"payload": payload_json}

        def serve():
            import threading as th

            while True:
                try:
                    conn, _ = srv.accept()
                except OSError:
                    return

                def handle(conn=conn):
                    req = b""
                    while b"\r\n\r\n" not in req:
                        ch = conn.recv(65536)
                        if not ch:
                            break
                        req += ch
                    if b"GET /v1.43/containers/json" not in req:
                        # surface protocol mismatches in the TEST, not
                        # as a swallowed OSError in the daemon thread
                        state["bad_request"] = bytes(req[:200])
                        conn.sendall(b"HTTP/1.1 400 Bad Request\r\n"
                                     b"content-length: 0\r\n\r\n")
                        conn.close()
                        return
                    body = state["payload"].encode()
                    # chunked framing: exercises the client's de-chunker
                    half = len(body) // 2
                    chunks = b""
                    for part in (body[:half], body[half:]):
                        chunks += (f"{len(part):x}\r\n".encode()
                                   + part + b"\r\n")
                    chunks += b"0\r\n\r\n"
                    conn.sendall(
                        b"HTTP/1.1 200 OK\r\n"
                        b"content-type: application/json\r\n"
                        b"transfer-encoding: chunked\r\n"
                        b"connection: close\r\n\r\n" + chunks)
                    conn.close()

                th.Thread(target=handle, daemon=True).start()

        import threading as th

        th.Thread(target=serve, daemon=True).start()
        return path, srv, state

    def test_labeled_containers_become_upstreams(self, tmp_path,
                                                 loop_runner):
        import json as jsonmod

        from pingoo_tpu.host.discovery import ServiceRegistry
        from pingoo_tpu.config.schema import ServiceConfig

        containers = [
            {   # labeled with explicit port
                "Id": "aaa111",
                "Labels": {"pingoo.service": "api", "pingoo.port": "8080"},
                "NetworkSettings": {"Networks": {
                    "bridge": {"IPAddress": "172.17.0.2"}}},
            },
            {   # single private port: inferred
                "Id": "bbb222",
                "Labels": {"pingoo.service": "api"},
                "Ports": [{"PrivatePort": 9000}],
                "NetworkSettings": {"Networks": {
                    "bridge": {"IPAddress": "172.17.0.3"}}},
            },
            {   # unlabeled: ignored
                "Id": "ccc333",
                "Labels": {},
                "NetworkSettings": {"Networks": {
                    "bridge": {"IPAddress": "172.17.0.4"}}},
            },
        ]
        path, srv, state = self._mock_daemon(
            tmp_path, jsonmod.dumps(containers))
        try:
            svc = ServiceConfig(name="api", http_proxy=())
            reg = ServiceRegistry([svc], enable_docker=True,
                                  enable_dns=False, docker_socket=path)
            loop_runner.run(reg.discover())
            ups = reg.get_upstreams("api")
            got = sorted((u.ip, u.port) for u in ups)
            assert "bad_request" not in state, state["bad_request"]
            assert got == [("172.17.0.2", 8080), ("172.17.0.3", 9000)], got
            # hot-swap: a container goes away -> next tick drops it
            state["payload"] = jsonmod.dumps(containers[:1])
            loop_runner.run(reg.discover())
            ups = reg.get_upstreams("api")
            assert [(u.ip, u.port) for u in ups] == [("172.17.0.2", 8080)]
        finally:
            srv.close()
