"""Literal-prefilter verdict cascade (ISSUE 4): soundness + parity.

The cascade's contract is structural: Stage A (compile-time factor
extraction + the packed shift-AND kernel) may only PRUNE work — the
candidate set must be a superset of the true match set for every
factor-gated pattern, and the end-to-end verdicts must be bit-identical
across PINGOO_PREFILTER=off|banks|compact and against the host
interpreter oracle. This file asserts all of that with randomized
rulesets/traffic, plus the satellite behaviors (batch dedup, metrics
schema coverage, the untouched ring ABI).
"""

import asyncio
import pickle
import random

import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.compiler.lowering import BLeaf, nfa_leaf_patterns
from pingoo_tpu.compiler.nfa import simulate
from pingoo_tpu.compiler.repat import (Quant, compile_regex,
                                       factor_present, literal_pattern,
                                       necessary_factor)
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import (RequestTuple, encode_requests,
                               evaluate_batch, make_verdict_fn)
from pingoo_tpu.engine.batch import RequestBatch, bucket_arrays
from pingoo_tpu.expr import compile_expression
from pingoo_tpu.ops.prefilter import (bank_to_prefilter_tables,
                                      build_prefilter_bank,
                                      prefilter_scan, scan_numpy)
from pingoo_tpu.utils.crs import (LFI_RCE_CORES, SQLI_CORES, XSS_CORES,
                                  generate_ruleset, generate_traffic)

CORPUS_PATTERNS = SQLI_CORES + XSS_CORES + LFI_RCE_CORES


def _random_match(rng: random.Random, lp) -> bytes:
    """A byte string biased to match `lp`: walk the positions choosing
    class members, with random padding when unanchored."""
    out = bytearray()
    if not lp.anchor_start and rng.random() < 0.7:
        out += bytes(rng.randrange(32, 127)
                     for _ in range(rng.randrange(0, 8)))
    for pos in lp.positions:
        if pos.quant == Quant.ONE:
            reps = 1
        elif pos.quant == Quant.OPT:
            reps = rng.randrange(0, 2)
        elif pos.quant == Quant.PLUS:
            reps = rng.randrange(1, 4)
        else:
            reps = rng.randrange(0, 4)
        choices = sorted(pos.bytes)
        out += bytes(rng.choice(choices) for _ in range(reps))
    if not (lp.anchor_end or lp.anchor_end_abs) and rng.random() < 0.7:
        out += bytes(rng.randrange(32, 127)
                     for _ in range(rng.randrange(0, 8)))
    return bytes(out)


class TestFactorExtraction:
    def test_factor_is_necessary_on_corpus_patterns(self):
        """Property (randomized): whenever a pattern matches a string,
        its extracted factor appears in that string — the soundness
        theorem of the whole cascade."""
        rng = random.Random(20260804)
        matched_total = 0
        for pat in CORPUS_PATTERNS:
            try:
                alts = compile_regex(pat)
            except Exception:
                continue
            for lp in alts:
                fac = necessary_factor(lp)
                if fac is None:
                    continue
                assert 1 <= len(fac) <= 12
                for _ in range(24):
                    s = _random_match(rng, lp)
                    if simulate(lp, s):
                        matched_total += 1
                        assert factor_present(fac, s), (pat, fac, s)
        assert matched_total > 200  # the property was actually exercised

    def test_factor_respects_quantifier_structure(self):
        # Interior PLUS breaks a window: a(b+)c matches "abbc" which has
        # no consecutive "abc" — the factor must be a 2-window.
        (lp,) = compile_regex("ab+c")
        fac = necessary_factor(lp)
        assert fac is not None and len(fac) == 2
        for s in (b"abc", b"abbbbc", b"xxabcyy"):
            assert simulate(lp, s) and factor_present(fac, s)

    def test_no_factor_for_weak_or_empty_patterns(self):
        for pat in ("a*b?", "x", ".{3}", "[a-z]+"):
            for lp in compile_regex(pat):
                assert necessary_factor(lp) is None, pat

    def test_case_fold_classes_ride_the_factor(self):
        lp = literal_pattern(b"UnIoN", case_insensitive=True)
        fac = necessary_factor(lp)
        assert fac is not None
        assert factor_present(fac, b"xxunionyy")
        assert factor_present(fac, b"xxUNIONyy")
        assert not factor_present(fac, b"xxonionyy")


class TestPrefilterKernel:
    def _random_factors(self, rng, n=40):
        out = []
        for _ in range(n):
            m = rng.randrange(2, 13)
            fac = []
            for _ in range(m):
                b = rng.randrange(33, 127)
                cls = {b}
                if rng.random() < 0.3:
                    cls.add(rng.randrange(33, 127))
                fac.append(frozenset(cls))
            out.append(tuple(fac))
        # dedupe (build_prefilter_bank packs whatever it is given; the
        # plan layer dedupes, so mirror that here)
        seen, uniq = set(), []
        for f in out:
            if f not in seen:
                seen.add(f)
                uniq.append(f)
        return uniq

    def test_kernel_matches_numpy_and_naive_oracles(self):
        rng = random.Random(7)
        factors = self._random_factors(rng)
        bank = build_prefilter_bank(factors)
        tables = bank_to_prefilter_tables(bank)
        B, L = 48, 40
        data = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros(B, dtype=np.int32)
        for i in range(B):
            n = rng.randrange(0, L + 1)
            row = bytes(rng.randrange(33, 127) for _ in range(n))
            if n and rng.random() < 0.5:  # embed a factor occurrence
                fac = factors[rng.randrange(len(factors))]
                emb = bytes(rng.choice(sorted(c)) for c in fac)
                p = rng.randrange(0, max(n - len(emb), 0) + 1)
                row = row[:p] + emb + row[p + len(emb):]
                row = row[:L]
                n = len(row)
            data[i, :n] = np.frombuffer(row, dtype=np.uint8)
            lens[i] = n
        ref = scan_numpy(bank, data, lens)
        naive = np.zeros_like(ref)
        for i in range(B):
            s = bytes(data[i, :lens[i]])
            for j, fac in enumerate(factors):
                naive[i, j] = factor_present(fac, s)
        np.testing.assert_array_equal(ref, naive)
        got = np.asarray(prefilter_scan(tables, data, lens))
        np.testing.assert_array_equal(got, ref)
        got_pl = np.asarray(
            prefilter_scan(tables, data, lens, backend="pallas"))
        np.testing.assert_array_equal(got_pl, ref)

    def test_padding_never_arms_a_factor(self):
        # A factor containing NUL would match the zero padding were the
        # length gate wrong.
        bank = build_prefilter_bank([(frozenset([0]), frozenset([0]))])
        data = np.zeros((2, 8), dtype=np.uint8)
        lens = np.array([0, 3], dtype=np.int32)
        assert not scan_numpy(bank, data, lens)[0].any()
        assert scan_numpy(bank, data, lens)[1].all()


@pytest.fixture(scope="module")
def crs_plan():
    rules, lists = generate_ruleset(120, with_lists=True,
                                    list_sizes=(256, 64))
    plan = compile_ruleset(rules, lists)
    reqs = generate_traffic(160, lists=lists, seed=9, attack_fraction=0.3)
    batch = encode_requests(reqs)
    b2 = RequestBatch(size=batch.size, arrays=bucket_arrays(batch.arrays))
    return rules, lists, plan, b2


class TestCandidateSuperset:
    def test_candidates_cover_every_match(self, crs_plan, monkeypatch):
        """Property (1): for every factor-gated leaf, candidate set ⊇
        true match set — checked leaf-by-leaf against the device matched
        matrix of the unprefiltered path."""
        rules, lists, plan, batch = crs_plan
        monkeypatch.setenv("PINGOO_PREFILTER", "off")
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), batch, lists)
        checked = 0
        for rule in plan.rules:
            if not isinstance(rule.ir, BLeaf):
                continue
            leaf = plan.leaves[rule.ir.leaf_id]
            binding = plan.bindings.get(rule.ir.leaf_id)
            if binding is None or binding.kind not in ("nfa", "window"):
                continue
            alts = [lp for lp in nfa_leaf_patterns(leaf)
                    if not lp.never_match]
            facs = [necessary_factor(lp) for lp in alts]
            if not facs or any(f is None for f in facs):
                continue  # always-scan leaf: never gated
            field = binding.field
            data = batch.arrays[f"{field}_bytes"]
            lens = batch.arrays[f"{field}_len"]
            for i in range(batch.size):
                if not matched[i, rule.index]:
                    continue
                s = bytes(data[i, :int(lens[i])])
                assert any(factor_present(f, s) for f in facs), (
                    rule.name, leaf, s)
                checked += 1
        assert checked > 10  # the superset property was exercised

    def test_gating_metadata_shape(self, crs_plan):
        _, _, plan, _ = crs_plan
        pf = plan.prefilter
        assert pf is not None and pf.fields
        for key, mask in pf.bank_masks.items():
            field = pf.bank_field[key]
            assert mask.shape[0] == pf.fields[field].num_factors
            assert len(pf.slot_codes[key]) >= 1
        assert plan.stats["prefilter_gated_banks"] >= 1


class TestModeParity:
    def test_end_to_end_parity_across_modes(self, crs_plan, monkeypatch):
        """Property (2) + (3): matched bitmaps bit-identical between
        off and each on mode, and equal to the host interpreter."""
        from pingoo_tpu.engine.batch import batch_to_contexts
        from pingoo_tpu.engine.verdict import interpret_rules_row

        rules, lists, plan, batch = crs_plan
        tables = plan.device_tables()
        monkeypatch.setenv("PINGOO_PREFILTER_LEVELS", "2")
        outs = {}
        for mode in ("off", "banks", "compact"):
            monkeypatch.setenv("PINGOO_PREFILTER", mode)
            outs[mode] = evaluate_batch(plan, make_verdict_fn(plan),
                                        tables, batch, lists)
        np.testing.assert_array_equal(outs["off"], outs["banks"])
        np.testing.assert_array_equal(outs["off"], outs["compact"])
        assert outs["off"].any(), "corpus traffic must match something"
        contexts = batch_to_contexts(batch, lists)
        for i in (0, 7, 31, 63, 100, 159):
            want = interpret_rules_row(plan, contexts[i])
            np.testing.assert_array_equal(outs["off"][i], want)

    def test_parity_across_seeds_and_small_batches(self, monkeypatch):
        """Randomized (hypothesis-style) sweep: fresh rulesets + odd
        batch sizes so the compaction ladder hits its degenerate shapes
        (count == 0, count == B, B below the ladder floor)."""
        monkeypatch.setenv("PINGOO_PREFILTER_LEVELS", "3")
        for seed, nreq in ((101, 40), (2027, 33)):
            rules, lists = generate_ruleset(
                60, with_lists=True, list_sizes=(64, 16), seed=seed)
            plan = compile_ruleset(rules, lists)
            reqs = generate_traffic(nreq, lists=lists, seed=seed + 1,
                                    attack_fraction=0.5)
            # all-clean tail exercises the zero-candidate skip branch
            reqs += generate_traffic(7, lists=lists, seed=seed + 2,
                                     attack_fraction=0.0)
            batch = encode_requests(reqs)
            b2 = RequestBatch(size=batch.size,
                              arrays=bucket_arrays(batch.arrays))
            tables = plan.device_tables()
            outs = {}
            for mode in ("off", "banks", "compact"):
                monkeypatch.setenv("PINGOO_PREFILTER", mode)
                outs[mode] = evaluate_batch(
                    plan, make_verdict_fn(plan), tables, b2, lists)
            np.testing.assert_array_equal(outs["off"], outs["banks"])
            np.testing.assert_array_equal(outs["off"], outs["compact"])

    def test_prefilter_fn_feeds_verdict(self, crs_plan, monkeypatch):
        """The service path (Stage A as its own dispatch feeding
        pf_hits) must agree with the inline-traced path."""
        from pingoo_tpu.engine.verdict import make_prefilter_fn

        rules, lists, plan, batch = crs_plan
        tables = plan.device_tables()
        monkeypatch.setenv("PINGOO_PREFILTER", "banks")
        pf = make_prefilter_fn(plan)
        n_gated = len(pf.gated)
        assert n_gated >= 1
        hits, aux = pf.fn(tables, batch.arrays)
        aux = np.asarray(aux)
        assert 0 <= int(aux[1]) <= n_gated
        fn = make_verdict_fn(plan)
        got = evaluate_batch(plan, lambda t, a: fn(t, a, hits),
                             tables, batch, lists)
        monkeypatch.setenv("PINGOO_PREFILTER", "off")
        want = evaluate_batch(plan, make_verdict_fn(plan), tables,
                              batch, lists)
        np.testing.assert_array_equal(got, want)

    def test_plan_prefilter_survives_pickle(self, crs_plan, monkeypatch):
        """PrefilterPlan + pf_ tables ride the artifact cache pickle."""
        rules, lists, plan, batch = crs_plan
        clone = pickle.loads(pickle.dumps(plan))
        assert clone.prefilter is not None
        assert set(clone.prefilter.fields) == set(plan.prefilter.fields)
        monkeypatch.setenv("PINGOO_PREFILTER", "banks")
        got = evaluate_batch(clone, make_verdict_fn(clone),
                             clone.device_tables(), batch, lists)
        monkeypatch.setenv("PINGOO_PREFILTER", "off")
        want = evaluate_batch(plan, make_verdict_fn(plan),
                              plan.device_tables(), batch, lists)
        np.testing.assert_array_equal(got, want)

    def test_ungated_ruleset_degrades_to_off(self, monkeypatch):
        """A ruleset with no extractable factor must behave exactly like
        off mode (no prefilter plan at all)."""
        rules = [RuleConfig(name="r0",
                            expression=compile_expression(
                                'client.asn > 100'),
                            actions=(Action.BLOCK,))]
        plan = compile_ruleset(rules, {})
        assert plan.prefilter is None
        monkeypatch.setenv("PINGOO_PREFILTER", "compact")
        batch = encode_requests([RequestTuple(asn=200),
                                 RequestTuple(asn=5)])
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), batch, {})
        assert matched[:, 0].tolist() == [True, False]


class TestBatchDedup:
    def test_duplicates_evaluated_once_and_fanned_out(self):
        from pingoo_tpu.engine.service import VerdictService

        rules = [RuleConfig(
            name="env",
            expression=compile_expression(
                'http_request.path.starts_with("/.env")'),
            actions=(Action.BLOCK,))]
        plan = compile_ruleset(rules, {})
        svc = VerdictService(plan, {}, max_batch=64, max_wait_us=200_000,
                             use_device=False)

        async def go():
            await svc.start()
            reqs = [RequestTuple(path="/.env", trace_id="a"),
                    RequestTuple(path="/.env", trace_id="b"),
                    RequestTuple(path="/ok", trace_id="c"),
                    RequestTuple(path="/.env", trace_id="d")]
            verdicts = await asyncio.gather(
                *(svc.evaluate(r) for r in reqs))
            await svc.stop()
            return verdicts

        verdicts = asyncio.run(go())
        assert [v.action for v in verdicts] == [1, 1, 0, 1]
        assert [bool(v.matched[0]) for v in verdicts] == [
            True, True, False, True]
        # 4 requests, 2 distinct tuples (trace_id excluded from the key)
        assert svc.stats.dedup_hits == 2
        assert svc.stats.snapshot()["dedup_hits"] == 2


class TestObservabilitySchema:
    def test_prefilter_metrics_schemad_and_wired(self):
        import os

        from pingoo_tpu.obs import schema

        assert "prefilter" in schema.VERDICT_STAGES
        assert set(schema.PREFILTER_METRICS) <= schema.all_metric_names()
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for rel in ("pingoo_tpu/engine/service.py",
                    "pingoo_tpu/native_ring.py"):
            with open(os.path.join(repo, rel)) as f:
                src = f.read()
            for name in schema.PREFILTER_METRICS:
                assert name in src, (rel, name)

    def test_service_stats_snapshot_has_prefilter_keys(self):
        from pingoo_tpu.engine.service import ServiceStats

        snap = ServiceStats().snapshot()
        assert "prefilter_candidate_rate" in snap
        assert "scan_banks_skipped" in snap
        assert "prefilter" in snap["stages"]


class TestRingAbiUntouched:
    def test_ring_abi_matches_committed_golden(self):
        """ISSUE 4 satellite: the cascade never touches the shm ring —
        the committed ABI golden must still match the numpy mirror
        without regeneration."""
        from tools.analyze import abi

        golden = abi.load_golden()
        assert golden, "committed abi_golden.json must exist"
        py = abi.python_table()
        assert abi.diff_tables(py, golden, "python", "golden") == []
