"""The driver's bench/dryrun artifacts must never be a crash or a hang.

Round 3 shipped BENCH_r03.json as rc=1 (parsed: null) and
MULTICHIP_r03.json as rc=124 (parent-process jax.devices() hung on the
wedged tunneled-TPU backend). These tests pin the round-4 guarantees:
bench.py always prints one parseable JSON line, and __graft_entry__'s
dryrun parent never initializes jax at all.
"""

import ast
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_degrades_to_cpu_diagnostic_when_backend_unavailable():
    """Round-5 guarantee (VERDICT r4 item 4): an unreachable accelerator
    must not leave the artifact at value 0 — the bench reruns the same
    pipeline on the CPU XLA backend, labeled `backend: cpu-diagnostic`,
    with the preflight failure recorded alongside."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "bogus"  # config.update raises fast in-probe
    env["BENCH_PROBE_RETRIES"] = "1"
    env["BENCH_PROBE_TIMEOUT"] = "60"
    env["BENCH_RULES"] = "40"  # keep the CPU run quick
    env["BENCH_BATCH"] = "128"
    env["BENCH_ITERS"] = "4"
    out = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=500)
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    assert lines, f"no output; stderr={out.stderr[-500:]}"
    data = json.loads(lines[-1])
    assert data["metric"] == "waf_requests_per_sec_per_chip_500rules"
    assert data["backend"] == "cpu-diagnostic"
    assert data["backend_probe_error"]
    assert data["value"] > 0  # never a zero artifact again
    assert "TFRT_CPU" in data["device"]  # honestly labeled
    assert out.returncode == 0


def test_dryrun_parent_never_touches_jax():
    """The parent half of dryrun_multichip must contain no jax import:
    a wedged backend hangs inside init (not an exception), so the only
    safe parent is one that re-execs before any jax use."""
    src = open(os.path.join(REPO, "__graft_entry__.py")).read()
    tree = ast.parse(src)
    fns = {n.name: n for n in ast.walk(tree)
           if isinstance(n, ast.FunctionDef)}

    def jax_import_lines(fn):
        lines = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Import) and any(
                    a.name == "jax" or a.name.startswith("jax.")
                    for a in node.names):
                lines.append(node.lineno)
            if isinstance(node, ast.ImportFrom) and (
                    node.module or "").startswith("jax"):
                lines.append(node.lineno)
        return lines

    # _reexec_dryrun (pure parent code) must not import jax at all.
    assert not jax_import_lines(fns["_reexec_dryrun"])
    # dryrun_multichip may import jax only AFTER the child-env guard
    # (which returns/re-execs in the parent), never before it.
    dm = fns["dryrun_multichip"]
    guard_line = None
    for node in ast.walk(dm):
        if (isinstance(node, ast.Call) and
                isinstance(node.func, ast.Attribute) and
                node.func.attr == "get" and
                any(isinstance(a, ast.Constant) and
                    a.value == "PINGOO_DRYRUN_CHILD" for a in node.args)):
            guard_line = node.lineno
            break
    assert guard_line is not None, "child-env guard missing"
    for line in jax_import_lines(dm):
        assert line > guard_line, (
            "dryrun_multichip imports jax before the child guard — a "
            "wedged backend would hang the driver parent")
