"""Native data plane v2: keep-alive per-request verdicts, body framing,
cookie gate (Ed25519 JWT), TLS termination with SNI, and tls-alpn-01 —
all driven over real sockets against the C++ binary.

Reference semantics under test: per-request rules evaluation
(http_listener.rs:133-274), the captcha gate ordering (:200-236), the
verified-client action loop (:251-264), and ClientHello-time challenge
interception (listeners/mod.rs:112-154, acme.rs:180-242).
"""

import asyncio
import hashlib
import http.server
import json
import os
import socket
import ssl
import subprocess
import threading
import time

import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.native_ring import Ring, RingSidecar

pytestmark = pytest.mark.skipif(
    not native_ring.ensure_built(), reason="native toolchain unavailable")

HTTPD = os.path.join(native_ring.NATIVE_DIR, "httpd")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Upstream(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_GET(self):
        body = f"up:{self.path}".encode()
        self.send_response(200)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        n = int(self.headers.get("content-length", 0))
        body = b"post:" + self.rfile.read(n)
        self.send_response(200)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


class NativeStack:
    """native httpd + ring sidecar + plain upstream (+ optional extras)."""

    def __init__(self, tmp, rules, lists=None, jwks=None, captcha_port=None,
                 tls_dir=None, alpn_dir=None, routes=None, services=None,
                 upstream_ca=None):
        from pingoo_tpu.compiler import compile_ruleset

        self.upstream = http.server.HTTPServer(("127.0.0.1", 0), _Upstream)
        threading.Thread(target=self.upstream.serve_forever,
                         daemon=True).start()
        plan = compile_ruleset(rules, lists or {}, routes=routes)
        self.ring_path = str(tmp / "ring")
        self.ring = Ring(self.ring_path, capacity=1024, create=True)
        self.sidecar = RingSidecar(
            self.ring, plan, lists or {}, max_batch=64,
            services=[name for name, _ in routes] if routes else None)
        threading.Thread(target=self.sidecar.run, daemon=True).start()
        self.port = _free_port()
        argv = [HTTPD, str(self.port), self.ring_path, "127.0.0.1",
                str(self.upstream.server_address[1])]
        if jwks:
            argv += ["--jwks", jwks]
        if captcha_port:
            argv += ["--captcha-upstream", f"127.0.0.1:{captcha_port}"]
        if tls_dir:
            argv += ["--tls-dir", tls_dir]
        if alpn_dir:
            argv += ["--alpn-dir", alpn_dir]
        self.services_path = None
        if services is not None:
            self.services_path = str(tmp / "services.tbl")
            native_ring.write_services_file(self.services_path, services)
            argv += ["--services", self.services_path]
        if upstream_ca:
            argv += ["--upstream-ca", upstream_ca]
        self.proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                     stderr=subprocess.PIPE)
        line = self.proc.stdout.readline()
        assert b"listening" in line, line

    def stop(self):
        self.proc.kill()
        self.proc.wait()
        self.upstream.shutdown()
        self.sidecar.stop()
        self.ring.close()


def recv_one_response(c):
    """Read one content-length-framed HTTP response from the socket."""
    data = b""
    while b"\r\n\r\n" not in data:
        ch = c.recv(65536)
        if not ch:
            return data
        data += ch
    head, rest = data.split(b"\r\n\r\n", 1)
    cl = 0
    for ln in head.split(b"\r\n"):
        if ln.lower().startswith(b"content-length:"):
            cl = int(ln.split(b":")[1])
    while len(rest) < cl:
        ch = c.recv(65536)
        if not ch:
            break
        rest += ch
    return head + b"\r\n\r\n" + rest[:cl]


def raw_request(port, payload):
    c = socket.create_connection(("127.0.0.1", port), timeout=10)
    c.sendall(payload)
    data = b""
    c.settimeout(10)
    try:
        while True:
            ch = c.recv(65536)
            if not ch:
                break
            data += ch
    except socket.timeout:
        pass
    c.close()
    return data


def _block_rules(marker="evil"):
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    return [RuleConfig(
        name="r", actions=(Action.BLOCK,),
        expression=compile_expression(
            f'http_request.url.contains("{marker}")'))]


class TestKeepAlive:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        st = NativeStack(tmp_path_factory.mktemp("ka"), _block_rules())
        yield st
        st.stop()

    def test_every_request_on_a_connection_is_verdicted(self, stack):
        """The WAF-bypass regression: request #2 on a kept-alive
        connection must be evaluated, not blindly relayed."""
        c = socket.create_connection(("127.0.0.1", stack.port), timeout=10)
        c.sendall(b"GET /one HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        r1 = recv_one_response(c)
        assert r1.startswith(b"HTTP/1.1 200") and b"up:/one" in r1
        c.sendall(b"GET /evil HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        r2 = recv_one_response(c)
        assert r2.startswith(b"HTTP/1.1 403")
        c.close()

    def test_pipelined_attack_blocked(self, stack):
        c = socket.create_connection(("127.0.0.1", stack.port), timeout=10)
        c.sendall(b"GET /a HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n"
                  b"GET /b-evil HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        r1 = recv_one_response(c)
        r2 = recv_one_response(c)
        c.close()
        assert r1.startswith(b"HTTP/1.1 200") and b"up:/a" in r1
        assert r2.startswith(b"HTTP/1.1 403")

    def test_post_body_then_reuse(self, stack):
        c = socket.create_connection(("127.0.0.1", stack.port), timeout=10)
        c.sendall(b"POST /p HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
                  b"content-length: 10\r\n\r\nhello-body")
        r1 = recv_one_response(c)
        assert b"post:hello-body" in r1
        c.sendall(b"GET /next HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        r2 = recv_one_response(c)
        assert b"up:/next" in r2
        c.close()

    def test_oversized_ua_403(self, stack):
        data = raw_request(
            stack.port,
            ("GET / HTTP/1.1\r\nhost: t\r\nuser-agent: " + "U" * 300 +
             "\r\nconnection: close\r\n\r\n").encode())
        assert data.startswith(b"HTTP/1.1 403")


class TestCookieGateAndCaptchaFlow:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.expr import compile_expression
        from pingoo_tpu.host.captcha import CaptchaManager
        from pingoo_tpu.host.httpd import HttpListener

        tmp = tmp_path_factory.mktemp("captcha")
        jwks = str(tmp / "jwks.json")
        cap = CaptchaManager(jwks_path=jwks)
        rules = [
            RuleConfig(name="bot", actions=(Action.CAPTCHA,),
                       expression=compile_expression(
                           'http_request.user_agent.contains("sqlmap")')),
            RuleConfig(name="cb", actions=(Action.CAPTCHA, Action.BLOCK),
                       expression=compile_expression(
                           'http_request.path == "/always-block"')),
        ]
        plan = compile_ruleset(rules, {})

        # Python control plane serving the captcha API behind the native
        # front (trust_xff so the client id binds the real client ip).
        loop = asyncio.new_event_loop()

        async def boot():
            svc = VerdictService(plan, {}, use_device=False, max_wait_us=100)
            lst = HttpListener("ctl", "127.0.0.1", 0, [], svc, {}, plan.rules,
                               cap, trust_xff=True)
            await svc.start()
            await lst.bind()
            asyncio.ensure_future(lst.serve_forever())
            return lst

        ctl = loop.run_until_complete(boot())
        threading.Thread(target=loop.run_forever, daemon=True).start()

        st = NativeStack(tmp, rules, jwks=jwks, captcha_port=ctl.bound_port)
        yield st
        st.stop()

    def _req(self, stack, method, path, headers=None, body=b"",
             ua="sqlmap/1.8"):
        h = f"{method} {path} HTTP/1.1\r\nhost: t.test\r\nuser-agent: {ua}\r\n"
        for k, v in (headers or {}).items():
            h += f"{k}: {v}\r\n"
        if body:
            h += f"content-length: {len(body)}\r\n"
        h += "connection: close\r\n\r\n"
        data = raw_request(stack.port, h.encode() + body)
        head, _, rest = data.partition(b"\r\n\r\n")
        status = int(head.split(b" ")[1])
        hdrs = {}
        for ln in head.split(b"\r\n")[1:]:
            k, _, v = ln.partition(b":")
            hdrs[k.decode().lower()] = v.strip().decode()
        return status, hdrs, rest

    def test_full_flow_solve_then_verified_proxy(self, stack):
        # 1) bot is redirected to the challenge
        st, h, _ = self._req(stack, "GET", "/")
        assert st == 302 and h.get("location") == "/__pingoo/captcha"
        # 2) init + PoW via the proxied control plane
        st, h, body = self._req(stack, "POST", "/__pingoo/captcha/api/init")
        assert st == 200
        payload = json.loads(body)
        cookie = h["set-cookie"].split(";")[0]
        nonce = 0
        while True:
            digest = hashlib.sha256(
                (payload["challenge"] + str(nonce)).encode()).hexdigest()
            if digest.startswith("0" * payload["difficulty"]):
                break
            nonce += 1
        st, h, body = self._req(
            stack, "POST", "/__pingoo/captcha/api/verify",
            headers={"cookie": cookie, "content-type": "application/json"},
            body=json.dumps({"nonce": str(nonce), "hash": digest}).encode())
        assert st == 200 and json.loads(body)["ok"] is True
        verified = h["set-cookie"].split(";")[0]
        # 3) the verified client is PROXIED, not redirected — the C++
        # plane verified the Ed25519 cookie itself.
        st, h, body = self._req(stack, "GET", "/",
                                headers={"cookie": verified})
        assert st == 200 and b"up:/" in body
        # 4) [Captcha, Block] still blocks a VERIFIED client (the
        # verdict byte's bit-2 lane).
        st, h, _ = self._req(stack, "GET", "/always-block",
                             headers={"cookie": verified})
        assert st == 403

    def test_tampered_cookie_redirected(self, stack):
        st, h, _ = self._req(
            stack, "GET", "/",
            headers={"cookie": "__pingoo_captcha_verified=ey.bad.sig"})
        assert st == 302 and h.get("location") == "/__pingoo/captcha"

    def test_captcha_path_reachable_with_bad_cookie(self, stack):
        """Reference ordering: /__pingoo/captcha is served BEFORE the
        cookie gate, so a stale cookie can always be cleared."""
        st, _, _ = self._req(
            stack, "POST", "/__pingoo/captcha/api/init",
            headers={"cookie": "__pingoo_captcha_verified=ey.bad.sig"})
        assert st == 200


class TestTlsPlane:
    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        from pingoo_tpu.host.tlsmgr import generate_self_signed

        tmp = tmp_path_factory.mktemp("tls")
        tls_dir = tmp / "tls"
        alpn_dir = tmp / "alpn"
        tls_dir.mkdir()
        alpn_dir.mkdir()
        for name, domains in [("default", ["localhost"]),
                              ("site.test", ["site.test"]),
                              ("_.wild.test", ["*.wild.test"])]:
            cert, key = generate_self_signed(domains)
            (tls_dir / f"{name}.pem").write_bytes(cert)
            (tls_dir / f"{name}.key").write_bytes(key)
        cert, key = generate_self_signed(["chal.test"])
        (alpn_dir / "chal.test.pem").write_bytes(cert)
        (alpn_dir / "chal.test.key").write_bytes(key)
        st = NativeStack(tmp, _block_rules(), tls_dir=str(tls_dir),
                         alpn_dir=str(alpn_dir))
        yield st
        st.stop()

    def _tls_conn(self, stack, server_name, alpn):
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        ctx.set_alpn_protocols(alpn)
        raw = socket.create_connection(("127.0.0.1", stack.port), timeout=10)
        return ctx.wrap_socket(raw, server_hostname=server_name)

    def _cert_sans(self, sock):
        from cryptography import x509

        pem = ssl.DER_cert_to_PEM_cert(sock.getpeercert(True))
        cert = x509.load_pem_x509_certificate(pem.encode())
        san = cert.extensions.get_extension_for_class(
            x509.SubjectAlternativeName).value
        return san.get_values_for_type(x509.DNSName)

    def test_https_request_verdicted_and_proxied(self, stack):
        c = self._tls_conn(stack, "localhost", ["http/1.1"])
        assert c.selected_alpn_protocol() == "http/1.1"
        c.sendall(b"GET /hello HTTP/1.1\r\nhost: localhost\r\n"
                  b"user-agent: ua\r\nconnection: close\r\n\r\n")
        data = b""
        try:
            while True:
                ch = c.recv(65536)
                if not ch:
                    break
                data += ch
        except ssl.SSLError:
            pass
        c.close()
        assert data.startswith(b"HTTP/1.1 200") and b"up:/hello" in data

    def test_https_attack_blocked(self, stack):
        c = self._tls_conn(stack, "localhost", ["http/1.1"])
        c.sendall(b"GET /x?evil HTTP/1.1\r\nhost: localhost\r\n"
                  b"user-agent: ua\r\nconnection: close\r\n\r\n")
        data = b""
        try:
            while True:
                ch = c.recv(65536)
                if not ch:
                    break
                data += ch
        except ssl.SSLError:
            pass
        c.close()
        assert data.startswith(b"HTTP/1.1 403")

    def test_sni_selects_exact_and_wildcard_cert(self, stack):
        c = self._tls_conn(stack, "site.test", ["http/1.1"])
        assert self._cert_sans(c) == ["site.test"]
        c.close()
        c = self._tls_conn(stack, "a.wild.test", ["http/1.1"])
        assert self._cert_sans(c) == ["*.wild.test"]
        c.close()

    def test_acme_tls_alpn_challenge(self, stack):
        """RFC 8737: acme-tls/1 must be NEGOTIATED and the ephemeral
        challenge certificate presented for the SNI name."""
        c = self._tls_conn(stack, "chal.test", ["acme-tls/1"])
        assert c.selected_alpn_protocol() == "acme-tls/1"
        assert self._cert_sans(c) == ["chal.test"]
        c.close()

    def test_acme_tls_alpn_unknown_domain_refused(self, stack):
        with pytest.raises((ssl.SSLError, ConnectionError, OSError)):
            self._tls_conn(stack, "unknown.test", ["acme-tls/1"])


class TestVerdictTimeoutFailsOpen:
    def test_awaiting_verdict_connection_fails_open(self, tmp_path):
        """A dead sidecar must not leak connections: after the verdict
        timeout the request is proxied without a verdict (fail-open,
        like the ring-full path)."""
        st = NativeStack(tmp_path, _block_rules())
        st.sidecar.stop()
        time.sleep(0.3)  # let the drain loop exit
        t0 = time.time()
        data = raw_request(
            st.port,
            b"GET /no-verdict HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
            b"connection: close\r\n\r\n")
        took = time.time() - t0
        st.stop()
        assert data.startswith(b"HTTP/1.1 200") and b"up:/no-verdict" in data
        assert took < 10, f"fail-open took {took:.1f}s"


class TestTlsAlpn01EndToEnd:
    def test_issuance_via_native_listener(self, tmp_path, loop_runner):
        """Full tls-alpn-01 issuance: the ACME client stages the RFC
        8737 challenge cert into --alpn-dir, the mock CA validates by a
        REAL acme-tls/1 handshake against the native listener, and the
        certificate is issued and installed."""
        import sys

        sys.path.insert(0, os.path.dirname(__file__))
        from test_acme import MockCa

        from pingoo_tpu.host.acme import AcmeManager
        from pingoo_tpu.host.tlsmgr import generate_self_signed

        tls_dir = tmp_path / "tls"
        alpn_dir = tmp_path / "alpn"
        tls_dir.mkdir()
        alpn_dir.mkdir()
        cert, key = generate_self_signed(["localhost"])
        (tls_dir / "default.pem").write_bytes(cert)
        (tls_dir / "default.key").write_bytes(key)

        stack = NativeStack(tmp_path, _block_rules(), tls_dir=str(tls_dir),
                            alpn_dir=str(alpn_dir))
        try:
            async def flow():
                ca = MockCa(challenge_type="tls-alpn-01")
                await ca.start()

                async def probe(domain):
                    def handshake():
                        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
                        ctx.check_hostname = False
                        ctx.verify_mode = ssl.CERT_NONE
                        ctx.set_alpn_protocols(["acme-tls/1"])
                        raw = socket.create_connection(
                            ("127.0.0.1", stack.port), timeout=10)
                        c = ctx.wrap_socket(raw, server_hostname=domain)
                        if c.selected_alpn_protocol() != "acme-tls/1":
                            c.close()
                            return None
                        der = c.getpeercert(True)
                        c.close()
                        return der

                    return await asyncio.get_running_loop().run_in_executor(
                        None, handshake)

                ca.alpn_probe = probe
                manager = AcmeManager(str(tls_dir), ["issued.test"],
                                      directory_url=ca.url("/dir"),
                                      alpn_dir=str(alpn_dir))
                try:
                    await manager.renew_all()
                finally:
                    await ca.stop()
                    await manager.client.close()
                return ca

            ca = loop_runner.run(flow())
        finally:
            stack.stop()

        assert len(ca.validated_keyauths) == 1
        assert (tls_dir / "issued.test.pem").exists()
        assert (tls_dir / "issued.test.key").exists()
        # Challenge certs are ephemeral: cleaned up after the order.
        assert list(alpn_dir.iterdir()) == []


class TestResponseFraming:
    @pytest.fixture()
    def raw_stack(self, tmp_path):
        """Native stack whose upstream is a raw socket server we script
        per-test (python http.server can't speak chunked/100-continue)."""
        from pingoo_tpu.compiler import compile_ruleset

        handler_box = {}
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)
        up_port = lsock.getsockname()[1]

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                h = handler_box.get("handler")
                if h:
                    threading.Thread(target=h, args=(conn,),
                                     daemon=True).start()
                else:
                    conn.close()

        threading.Thread(target=serve, daemon=True).start()

        plan = compile_ruleset(_block_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        threading.Thread(target=sidecar.run, daemon=True).start()
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "ring"), "127.0.0.1",
             str(up_port)], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()

        class S:
            pass

        s = S()
        s.port = port
        s.handler_box = handler_box
        yield s
        proc.kill()
        proc.wait()
        lsock.close()
        sidecar.stop()
        ring.close()

    @staticmethod
    def _read_head(conn):
        data = b""
        while b"\r\n\r\n" not in data:
            ch = conn.recv(65536)
            if not ch:
                return data
            data += ch
        return data

    def test_chunked_response_relayed_and_keepalive(self, raw_stack):
        def handler(conn):
            self._read_head(conn)
            conn.sendall(b"HTTP/1.1 200 OK\r\ntransfer-encoding: chunked\r\n"
                         b"connection: close\r\n\r\n"
                         b"5\r\nhello\r\n6\r\n world\r\n0\r\n\r\n")
            conn.close()

        raw_stack.handler_box["handler"] = handler
        c = socket.create_connection(("127.0.0.1", raw_stack.port),
                                     timeout=10)
        c.sendall(b"GET /c HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        data = b""
        c.settimeout(10)
        while b"0\r\n\r\n" not in data:
            data += c.recv(65536)
        assert data.startswith(b"HTTP/1.1 200")
        assert b"hello" in data and b" world" in data
        # upstream said connection: close, but the proxy reframes:
        # chunked framing lets the client connection stay alive.
        assert b"connection: keep-alive" in data.lower()
        c.sendall(b"GET /c2 HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        data2 = b""
        while b"0\r\n\r\n" not in data2:
            data2 += c.recv(65536)
        assert data2.startswith(b"HTTP/1.1 200")
        c.close()

    def test_100_continue_interim_passthrough(self, raw_stack):
        def handler(conn):
            head = self._read_head(conn)
            assert b"expect" in head.lower()
            conn.sendall(b"HTTP/1.1 100 Continue\r\n\r\n")
            # read the body (4 bytes)
            body = b""
            while len(body) < 4:
                body += conn.recv(1024)
            resp = b"got:" + body
            conn.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: " +
                         str(len(resp)).encode() + b"\r\n\r\n" + resp)
            conn.close()

        raw_stack.handler_box["handler"] = handler
        c = socket.create_connection(("127.0.0.1", raw_stack.port),
                                     timeout=10)
        c.sendall(b"POST /e HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
                  b"expect: 100-continue\r\ncontent-length: 4\r\n\r\n")
        c.settimeout(10)
        interim = self._read_head(c)
        assert interim.startswith(b"HTTP/1.1 100")
        c.sendall(b"BODY")
        data = interim[len(b"HTTP/1.1 100 Continue\r\n\r\n"):]
        while b"got:BODY" not in data:
            ch = c.recv(65536)
            if not ch:
                break
            data += ch
        assert b"HTTP/1.1 200" in data and b"got:BODY" in data
        c.close()

    def test_half_closed_client_times_out_not_spins(self, raw_stack):
        """A client that half-closes mid-proxy must be reaped by the
        idle sweep (the EOF disarms the read side; no busy loop)."""
        def handler(conn):
            self._read_head(conn)
            time.sleep(30)  # upstream never answers
            conn.close()

        raw_stack.handler_box["handler"] = handler
        c = socket.create_connection(("127.0.0.1", raw_stack.port),
                                     timeout=10)
        c.sendall(b"GET /h HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n\r\n")
        time.sleep(0.3)
        c.shutdown(socket.SHUT_WR)  # half-close during proxying
        # The connection must not consume CPU; give the sweep a moment
        # and confirm the process is still healthy by a second request.
        time.sleep(1.2)
        data = raw_stack.handler_box  # keep reference
        c2 = socket.create_connection(("127.0.0.1", raw_stack.port),
                                      timeout=10)
        c2.sendall(b"GET /evil HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
                   b"connection: close\r\n\r\n")
        resp = b""
        c2.settimeout(10)
        try:
            while True:
                ch = c2.recv(65536)
                if not ch:
                    break
                resp += ch
        except socket.timeout:
            pass
        assert resp.startswith(b"HTTP/1.1 403")
        c.close()
        c2.close()
        assert data is raw_stack.handler_box


class TestNativeH2:
    """HTTP/2 on the C++ data plane (nghttp2 ABI shim): cleartext prior
    knowledge and TLS ALPN, per-stream verdicts through the ring."""

    @pytest.fixture(scope="class")
    def stack(self, tmp_path_factory):
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        st = NativeStack(tmp_path_factory.mktemp("nh2"), _block_rules())
        yield st
        st.stop()

    def _request(self, port, method, path, headers, body=b"", ssl_ctx=None,
                 server_hostname=None):
        import asyncio

        from pingoo_tpu.host.h2 import H2UpstreamConnection

        async def flow():
            conn = H2UpstreamConnection("127.0.0.1", port)
            await conn.connect(ssl=ssl_ctx, server_hostname=server_hostname)
            try:
                return await asyncio.wait_for(
                    conn.request(method, "t.test", path, headers, body), 10)
            finally:
                await conn.close()

        return asyncio.run(flow())

    def test_prior_knowledge_verdicts(self, stack):
        st, _, body = self._request(stack.port, "GET", "/ok",
                                    [("user-agent", "ua")])
        assert st == 200 and b"up:/ok" in body
        st, _, _ = self._request(stack.port, "GET", "/x?evil",
                                 [("user-agent", "ua")])
        assert st == 403

    def test_post_body_forwarded(self, stack):
        st, _, body = self._request(stack.port, "POST", "/p",
                                    [("user-agent", "ua")], b"h2-native")
        assert st == 200 and b"post:h2-native" in body

    def test_empty_ua_blocked(self, stack):
        st, _, _ = self._request(stack.port, "GET", "/", [])
        assert st == 403

    def test_multiplexed_streams_sequential_service(self, stack):
        import asyncio

        from pingoo_tpu.host.h2 import H2UpstreamConnection

        async def flow():
            conn = H2UpstreamConnection("127.0.0.1", stack.port)
            await conn.connect()
            try:
                return await asyncio.gather(
                    conn.request("GET", "t.test", "/a",
                                 [("user-agent", "ua")]),
                    conn.request("GET", "t.test", "/b?evil",
                                 [("user-agent", "ua")]),
                    conn.request("GET", "t.test", "/c",
                                 [("user-agent", "ua")]),
                )
            finally:
                await conn.close()

        a, b, c = asyncio.run(flow())
        assert a[0] == 200 and b"/a" in a[2]
        assert b[0] == 403
        assert c[0] == 200 and b"/c" in c[2]

    def test_h1_coexists(self, stack):
        data = raw_request(
            stack.port,
            b"GET /h1 HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
            b"connection: close\r\n\r\n")
        assert data.startswith(b"HTTP/1.1 200") and b"up:/h1" in data


class TestNativeH2OverTls:
    def test_alpn_h2_and_verdicts(self, tmp_path):
        from pingoo_tpu.host import h2 as h2mod
        from pingoo_tpu.host.tlsmgr import generate_self_signed

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        tls_dir = tmp_path / "tls"
        tls_dir.mkdir()
        cert, key = generate_self_signed(["localhost"])
        (tls_dir / "default.pem").write_bytes(cert)
        (tls_dir / "default.key").write_bytes(key)
        stack = NativeStack(tmp_path, _block_rules(), tls_dir=str(tls_dir))
        try:
            import asyncio

            from pingoo_tpu.host.h2 import H2UpstreamConnection

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            ctx.set_alpn_protocols(["h2"])

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", stack.port)
                await conn.connect(ssl=ctx, server_hostname="localhost")
                try:
                    ok = await asyncio.wait_for(
                        conn.request("GET", "t.test", "/tls",
                                     [("user-agent", "ua")]), 10)
                    bad = await asyncio.wait_for(
                        conn.request("GET", "t.test", "/x?evil",
                                     [("user-agent", "ua")]), 10)
                    return ok, bad
                finally:
                    await conn.close()

            ok, bad = asyncio.run(flow())
            assert ok[0] == 200 and b"up:/tls" in ok[2]
            assert bad[0] == 403
        finally:
            stack.stop()


class TestNativeH2ChunkedUpstream:
    def test_chunked_upstream_deframed(self, tmp_path):
        """An h1 upstream answering chunked must reach the h2 client as
        clean DATA frames (no chunk metadata leaking)."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")

        handler_box = {}
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                data = b""
                while b"\r\n\r\n" not in data:
                    ch = conn.recv(65536)
                    if not ch:
                        break
                    data += ch
                conn.sendall(b"HTTP/1.1 200 OK\r\n"
                             b"transfer-encoding: chunked\r\n\r\n"
                             b"5\r\nhello\r\n6\r\n-world\r\n0\r\n\r\n")
                conn.close()

        threading.Thread(target=serve, daemon=True).start()

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.native_ring import Ring, RingSidecar

        plan = compile_ruleset(_block_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        threading.Thread(target=sidecar.run, daemon=True).start()
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "ring"), "127.0.0.1",
             str(lsock.getsockname()[1])], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()
        try:
            import asyncio

            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    return await asyncio.wait_for(
                        conn.request("GET", "t.test", "/c",
                                     [("user-agent", "ua")]), 10)
                finally:
                    await conn.close()

            status, headers, body = asyncio.run(flow())
            assert status == 200
            assert body == b"hello-world"  # de-chunked, exact payload
        finally:
            proc.kill()
            proc.wait()
            lsock.close()
            sidecar.stop()
            ring.close()


class TestNativeH2StreamEdges:
    """h2 proxying edge behavior against hand-rolled upstreams/clients:
    truncated upstream bodies and stalled (non-reading) clients."""

    def test_truncated_cl_response_resets_stream(self, tmp_path):
        """An upstream dying mid content-length body must NOT become a
        well-formed short response over h2 — the stream is reset so the
        client can see the failure."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")

        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                data = b""
                while b"\r\n\r\n" not in data:
                    ch = conn.recv(65536)
                    if not ch:
                        break
                    data += ch
                conn.sendall(b"HTTP/1.1 200 OK\r\ncontent-length: 1000"
                             b"\r\n\r\npartial")
                conn.close()  # truncated

        threading.Thread(target=serve, daemon=True).start()

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.native_ring import Ring, RingSidecar

        plan = compile_ruleset(_block_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        threading.Thread(target=sidecar.run, daemon=True).start()
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "ring"), "127.0.0.1",
             str(lsock.getsockname()[1])], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()
        try:
            import asyncio

            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    with pytest.raises(ConnectionError, match="reset"):
                        await asyncio.wait_for(
                            conn.request("GET", "t.test", "/t",
                                         [("user-agent", "ua")]), 10)
                finally:
                    await conn.close()

            asyncio.run(flow())
        finally:
            proc.kill()
            proc.wait()
            lsock.close()
            sidecar.stop()
            ring.close()


    def test_interim_1xx_forwarded_on_h2(self, tmp_path):
        """An upstream 100 Continue must be relayed as a non-final h2
        HEADERS (hyper forwards interim responses) without corrupting
        the final response on the same stream."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")

        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                data = b""
                while b"\r\n\r\n" not in data:
                    ch = conn.recv(65536)
                    if not ch:
                        break
                    data += ch
                conn.sendall(
                    b"HTTP/1.1 100 Continue\r\nserver: leaky\r\n\r\n"
                    b"HTTP/1.1 200 OK\r\ncontent-length: 2\r\n\r\nok")
                conn.close()

        threading.Thread(target=serve, daemon=True).start()

        from pingoo_tpu.compiler import compile_ruleset

        plan = compile_ruleset(_block_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        threading.Thread(target=sidecar.run, daemon=True).start()
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "ring"), "127.0.0.1",
             str(lsock.getsockname()[1])], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()
        try:
            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    return await asyncio.wait_for(
                        conn.request("GET", "t.test", "/t",
                                     [("user-agent", "ua")]), 10)
                finally:
                    await conn.close()

            st, headers, body = asyncio.run(flow())
            assert st == 200 and body == b"ok"
            # the interim head's identity header must not leak through
            assert ("server", "leaky") not in headers
        finally:
            proc.kill()
            proc.wait()
            lsock.close()
            sidecar.stop()
            ring.close()

    def test_stalled_client_bounds_buffering(self, tmp_path):
        """h2 client-side backpressure: a client that raises its
        flow-control windows sky-high and then never reads its socket
        must NOT make httpd buffer the upstream response without bound.
        h2_flush stops pulling frames at the outbuf cap and
        h2_update_stream_events pauses the upstream read, so the bytes
        httpd drains from an endless upstream plateau near
        kMaxBuffered + kH2PendingCap + kernel socket buffers."""
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)
        sent = [0]

        def serve():
            while True:
                try:
                    conn, _ = lsock.accept()
                except OSError:
                    return
                data = b""
                while b"\r\n\r\n" not in data:
                    ch = conn.recv(65536)
                    if not ch:
                        break
                    data += ch
                # Endless EOF-framed response: stream until the proxy
                # stops reading (send blocks) or the test tears down.
                try:
                    conn.sendall(b"HTTP/1.1 200 OK\r\n\r\n")
                    chunk = b"x" * 65536
                    conn.settimeout(1.0)
                    while True:
                        conn.sendall(chunk)
                        sent[0] += len(chunk)
                except OSError:
                    pass
                finally:
                    conn.close()

        threading.Thread(target=serve, daemon=True).start()

        from pingoo_tpu.compiler import compile_ruleset

        plan = compile_ruleset(_block_rules(), {})
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=32)
        threading.Thread(target=sidecar.run, daemon=True).start()
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "ring"), "127.0.0.1",
             str(lsock.getsockname()[1])], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()
        c = None
        try:
            # Hand-rolled h2 client: preface, SETTINGS raising
            # INITIAL_WINDOW_SIZE to max, a huge connection
            # WINDOW_UPDATE, one GET — then never read.
            def frame(ftype, flags, stream, payload):
                return (len(payload).to_bytes(3, "big")
                        + bytes([ftype, flags])
                        + stream.to_bytes(4, "big") + payload)

            settings = frame(0x4, 0, 0,
                             (4).to_bytes(2, "big")
                             + (2**31 - 1).to_bytes(4, "big"))
            winupd = frame(0x8, 0, 0, (2**30).to_bytes(4, "big"))
            hpack = (b"\x82"            # :method GET (static 2)
                     b"\x86"            # :scheme http (static 6)
                     b"\x44\x04/big"    # :path literal, name static 4
                     b"\x41\x06t.test"  # :authority
                     b"\x7a\x02ua")     # user-agent
            headers = frame(0x1, 0x5, 1, hpack)  # END_STREAM|END_HEADERS
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            # Shrink our receive buffer so the kernel absorbs little on
            # the stalled side and httpd's caps do the bounding.
            c.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
            c.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
                      + settings + winupd + headers
                      + frame(0x4, 0x1, 0, b""))  # ack server SETTINGS
            # Wait for the verdict + proxying to start, then give the
            # upstream time to push as much as httpd will take.
            deadline = time.time() + 20
            last = -1
            while time.time() < deadline:
                time.sleep(1.0)
                if sent[0] == last and sent[0] > 0:
                    break  # upstream send has blocked: backpressure
                last = sent[0]
            # kMaxBuffered (1 MiB) + kH2PendingCap (256 KiB) + kernel
            # socket buffers on both hops; 16 MiB of headroom vs the
            # endless stream proves the read side actually paused.
            assert 0 < sent[0] < 16 * 1024 * 1024, sent[0]
        finally:
            if c is not None:
                c.close()
            proc.kill()
            proc.wait()
            lsock.close()
            sidecar.stop()
            ring.close()


class _TaggedUpstream(http.server.BaseHTTPRequestHandler):
    """Echoes its server's tag so routing tests can see which upstream
    serviced the request."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):
        delay = getattr(self.server, "delay_s", 0)
        if delay:
            time.sleep(delay)
        body = f"{self.server.tag}:{self.path}".encode()
        self.send_response(200)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


def _tagged_upstream(tag, delay_s=0):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _TaggedUpstream)
    srv.tag = tag
    srv.delay_s = delay_s
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestNativeRouting:
    """VERDICT r2 item 1: the native plane as the front door — per-request
    service routing from the verdict byte's route bits, registry-fed
    multi-upstream with hot reload, and SIGTERM drain. Reference:
    http_listener.rs:266-270 (first matching service),
    http_proxy_service.rs:101,118 (random upstream), listeners/mod.rs:28
    (drain cap)."""

    def _routes(self):
        from pingoo_tpu.expr import compile_expression

        return [("api", compile_expression(
                    'http_request.path.starts_with("/api")')),
                ("web", None)]  # no route -> match-all fallback

    def _get(self, port, path, timeout=8.0):
        payload = (f"GET {path} HTTP/1.1\r\nhost: t.test\r\n"
                   "user-agent: routed/1.0\r\nconnection: close\r\n\r\n")
        return raw_request(port, payload.encode())

    def _get_until(self, port, path, want: bytes, tries=25):
        """Retry until routing reflects `want` (first requests may fail
        open to service 0 while the sidecar's first batch compiles)."""
        out = b""
        for _ in range(tries):
            out = self._get(port, path)
            if want in out:
                return out
            time.sleep(0.4)
        return out

    def test_two_services_routed_and_hot_swapped(self, tmp_path):
        a = _tagged_upstream("svc-a")
        b = _tagged_upstream("svc-b")
        c = _tagged_upstream("svc-c")
        services = [("api", [("127.0.0.1", a.server_address[1])]),
                    ("web", [("127.0.0.1", b.server_address[1])])]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services)
        try:
            out = self._get_until(stack.port, "/api/v1", b"svc-a")
            assert b"svc-a:/api/v1" in out, out[:200]
            out = self._get(stack.port, "/index.html")
            assert b"svc-b:/index.html" in out, out[:200]
            # hot swap: the registry repoints api at svc-c; the C++ plane
            # reloads the table on mtime change without restarting
            native_ring.write_services_file(
                stack.services_path,
                [("api", [("127.0.0.1", c.server_address[1])]),
                 ("web", [("127.0.0.1", b.server_address[1])])])
            out = self._get_until(stack.port, "/api/v2", b"svc-c")
            assert b"svc-c:/api/v2" in out, out[:200]
            # web unaffected by the swap
            out = self._get(stack.port, "/w")
            assert b"svc-b:/w" in out, out[:200]
        finally:
            stack.stop()
            for srv in (a, b, c):
                srv.shutdown()

    def test_random_upstream_choice_spreads(self, tmp_path):
        a1 = _tagged_upstream("m1")
        a2 = _tagged_upstream("m2")
        services = [("api", [("127.0.0.1", a1.server_address[1]),
                             ("127.0.0.1", a2.server_address[1])]),
                    ("web", [("127.0.0.1", a1.server_address[1])])]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services)
        try:
            self._get_until(stack.port, "/api/x", b"m")
            seen = set()
            for _ in range(40):
                out = self._get(stack.port, "/api/x")
                if b"m1:" in out:
                    seen.add("m1")
                if b"m2:" in out:
                    seen.add("m2")
                if len(seen) == 2:
                    break
            assert seen == {"m1", "m2"}, seen
        finally:
            stack.stop()
            a1.shutdown()
            a2.shutdown()

    def test_no_matching_service_404(self, tmp_path):
        from pingoo_tpu.expr import compile_expression

        a = _tagged_upstream("only")
        routes = [("api", compile_expression(
            'http_request.path.starts_with("/api")'))]
        services = [("api", [("127.0.0.1", a.server_address[1])])]
        stack = NativeStack(tmp_path, rules=[], routes=routes,
                            services=services)
        try:
            out = self._get_until(stack.port, "/api/ok", b"only")
            assert b"only:/api/ok" in out
            out = self._get(stack.port, "/nope")
            assert out.split(b"\r\n")[0].endswith(b"404 Not Found"), out[:80]
        finally:
            stack.stop()
            a.shutdown()

    def test_sigterm_drains_in_flight_request(self, tmp_path):
        import signal

        slow = _tagged_upstream("slow", delay_s=1.0)
        services = [("api", [("127.0.0.1", slow.server_address[1])]),
                    ("web", [("127.0.0.1", slow.server_address[1])])]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services)
        try:
            # warm the verdict path so the in-flight request is verdicted
            self._get_until(stack.port, "/warm", b"slow")
            conn = socket.create_connection(("127.0.0.1", stack.port),
                                            timeout=10)
            conn.sendall(b"GET /slow HTTP/1.1\r\nhost: t\r\n"
                         b"user-agent: u\r\n\r\n")
            time.sleep(0.3)  # request reaches the upstream
            stack.proc.send_signal(signal.SIGTERM)
            data = b""
            conn.settimeout(10)
            try:
                while b"slow:/slow" not in data:
                    ch = conn.recv(4096)
                    if not ch:
                        break
                    data += ch
            except socket.timeout:
                pass
            assert b"slow:/slow" in data, data[:200]  # drained, not dropped
            rc = stack.proc.wait(timeout=10)
            assert rc == 0
            conn.close()
        finally:
            if stack.proc.poll() is None:
                stack.stop()
            else:
                stack.upstream.shutdown()
                stack.sidecar.stop()
                stack.ring.close()
            slow.shutdown()


class TestUpstreamPooling:
    """Pooled keep-alive upstream connections: sequential proxied
    requests must reuse the upstream connection instead of opening one
    per request (reference pools its client, http_proxy_service.rs:54-71)."""

    def test_sequential_requests_reuse_upstream_connection(self, tmp_path):
        accepts = []

        class CountingUpstream(http.server.ThreadingHTTPServer):
            def get_request(self):
                req = super().get_request()
                accepts.append(req[1])
                return req

        srv = CountingUpstream(("127.0.0.1", 0), _TaggedUpstream)
        srv.tag = "pool"
        srv.delay_s = 0
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        stack = NativeStack(tmp_path, rules=[])
        # point httpd at the counting upstream instead of the stack's
        stack.proc.kill()
        stack.proc.wait()
        stack.proc = subprocess.Popen(
            [HTTPD, str(stack.port), stack.ring_path, "127.0.0.1",
             str(srv.server_address[1])],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        assert b"listening" in stack.proc.stdout.readline()
        try:
            n = 12
            ok = 0
            for i in range(n):
                out = raw_request(
                    stack.port,
                    f"GET /r{i} HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    "connection: close\r\n\r\n".encode())
                if f"pool:/r{i}".encode() in out:
                    ok += 1
            assert ok == n, (ok, n)
            # All 12 proxied requests over a handful of pooled upstream
            # connections (first request per idle moment may open one).
            assert len(accepts) < n, (len(accepts), n)
        finally:
            stack.stop()
            srv.shutdown()


class TestOverflowFieldParity:
    """VERDICT r2 item 5: a >2048-byte URL must still match content
    rules past the slot cap when fronted by the C++ plane — the spill
    side-channel carries the full strings to the sidecar."""

    def test_4kb_url_blocked_beyond_slot_cap(self, tmp_path):
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(
            name="deep", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.url.contains("XNEEDLEX")'))]
        stack = NativeStack(tmp_path, rules)
        try:
            deep = "/" + "a" * 4000 + "XNEEDLEX"  # marker past byte 2048
            out = raw_request(
                stack.port,
                (f"GET {deep} HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                 "connection: close\r\n\r\n").encode())
            assert out.split(b"\r\n")[0].endswith(b"403 Forbidden"), out[:80]
            # same-shape clean URL still proxied
            clean = "/" + "a" * 4000 + "ZZZZ"
            out = raw_request(
                stack.port,
                (f"GET {clean} HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                 "connection: close\r\n\r\n").encode())
            assert b"200" in out.split(b"\r\n")[0], out[:80]
            assert stack.sidecar.spilled_rows >= 2
        finally:
            stack.stop()


class TestNativeMetrics:
    """VERDICT r2 item 8: the native plane and the ring sidecar — the
    actual serving path — expose their own metrics."""

    def test_metrics_endpoint_and_sidecar_stats(self, tmp_path):
        stack = NativeStack(tmp_path, _block_rules())
        try:
            for path, ua in (("/ok", "u"), ("/x-evil", "u"), ("/ok2", "u"),
                             ("/noua", "")):
                h = (f"GET {path} HTTP/1.1\r\nhost: t\r\n" +
                     (f"user-agent: {ua}\r\n" if ua else "") +
                     "connection: close\r\n\r\n")
                raw_request(stack.port, h.encode())
            out = raw_request(
                stack.port,
                b"GET /__pingoo/metrics HTTP/1.1\r\nhost: t\r\n"
                b"user-agent: u\r\naccept: application/json\r\n"
                b"connection: close\r\n\r\n")
            head, _, body = out.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.1 200")
            m = json.loads(body)
            assert m["requests"] >= 3
            assert m["blocked"] >= 1          # /x-evil
            assert m["ua_rejected"] >= 1      # /noua
            assert m["verdicts"] >= 3
            hist_total = sum(m["verdict_wait_ms_hist"].values())
            assert hist_total == m["verdicts"]
            assert "ring_pending" in m and "pooled_upstreams" in m
            # shm ring telemetry block (ring v4) rides the same scrape.
            assert m["ring"]["enqueued"] >= 3
            assert m["ring"]["verdicts_posted"] >= 3
            assert m["ring"]["depth_hwm"] >= 1
            # Default exposition (no Accept) is Prometheus text with
            # the shared metric names.
            out = raw_request(
                stack.port,
                b"GET /__pingoo/metrics HTTP/1.1\r\nhost: t\r\n"
                b"user-agent: u\r\nconnection: close\r\n\r\n")
            head, _, body = out.partition(b"\r\n\r\n")
            assert head.startswith(b"HTTP/1.1 200")
            assert b"text/plain" in head
            text = body.decode()
            assert "pingoo_requests_total{plane=\"native\"}" in text
            assert "pingoo_verdict_wait_ms_bucket" in text
            from pingoo_tpu.obs.registry import lint_prometheus_text

            assert lint_prometheus_text(text) == []
            st = stack.sidecar.stats()
            assert st["processed"] >= 3
            assert st["batches"] >= 1
            assert st["batch_occupancy"] > 0
            assert st["device_wait_ms_per_batch"] >= 0
            assert st["ring_telemetry"]["dequeued"] >= 3
        finally:
            stack.stop()


def _ws_echo_upstream():
    """Minimal upgrade-accepting upstream: answers the RFC 6455
    handshake and echoes raw bytes after the 101."""
    import base64
    import hashlib

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)

    def serve():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=handle, args=(c,), daemon=True).start()

    def handle(c):
        data = b""
        while b"\r\n\r\n" not in data:
            ch = c.recv(4096)
            if not ch:
                c.close()
                return
            data += ch
        head, _, rest = data.partition(b"\r\n\r\n")
        key = b""
        for ln in head.split(b"\r\n"):
            if ln.lower().startswith(b"sec-websocket-key:"):
                key = ln.split(b":", 1)[1].strip()
        accept = base64.b64encode(hashlib.sha1(
            key + b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11").digest())
        c.sendall(b"HTTP/1.1 101 Switching Protocols\r\n"
                  b"upgrade: websocket\r\nconnection: Upgrade\r\n"
                  b"sec-websocket-accept: " + accept + b"\r\n\r\n")
        if rest:
            c.sendall(rest)  # echo early frames
        while True:
            try:
                ch = c.recv(4096)
            except OSError:
                break
            if not ch:
                break
            c.sendall(ch)
        c.close()

    threading.Thread(target=serve, daemon=True).start()
    return srv


class TestWebSocketPassthrough:
    """VERDICT r2 item 9: Upgrade requests tunnel through the plane
    after the verdict instead of losing their Upgrade headers."""

    def test_ws_echo_through_native_plane(self, tmp_path):
        ws = _ws_echo_upstream()
        stack = NativeStack(tmp_path, _block_rules())
        stack.proc.kill()
        stack.proc.wait()
        stack.proc = subprocess.Popen(
            [HTTPD, str(stack.port), stack.ring_path, "127.0.0.1",
             str(ws.getsockname()[1])],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        assert b"listening" in stack.proc.stdout.readline()
        try:
            c = socket.create_connection(("127.0.0.1", stack.port),
                                         timeout=10)
            c.sendall(b"GET /chat HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                      b"connection: Upgrade\r\nupgrade: websocket\r\n"
                      b"sec-websocket-key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
                      b"sec-websocket-version: 13\r\n\r\n")
            head = b""
            c.settimeout(10)
            while b"\r\n\r\n" not in head:
                head += c.recv(4096)
            assert head.startswith(b"HTTP/1.1 101"), head[:120]
            assert b"sec-websocket-accept:" in head.lower()
            payload, _, early = head.partition(b"\r\n\r\n")
            # raw bytes flow both directions after the 101
            c.sendall(b"\x81\x05hello")  # a ws text frame (unmasked test)
            got = early
            while len(got) < 7:
                got += c.recv(4096)
            assert got == b"\x81\x05hello", got
            c.sendall(b"ping2")
            got = b""
            while len(got) < 5:
                got += c.recv(4096)
            assert got == b"ping2"
            c.close()
            # a blocked path is still blocked before any upgrade
            out = raw_request(
                stack.port,
                b"GET /x-evil HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                b"connection: Upgrade\r\nupgrade: websocket\r\n"
                b"connection: close\r\n\r\n")
            assert b"403" in out.split(b"\r\n")[0]
        finally:
            stack.stop()
            ws.close()


class _DelayEchoUpstream(http.server.BaseHTTPRequestHandler):
    """Path-programmable upstream: /slow waits 1s; /big streams 4 MiB."""

    protocol_version = "HTTP/1.1"

    def do_GET(self):
        if self.path.startswith("/slow"):
            time.sleep(1.0)
        if self.path.startswith("/big"):
            size = 4 * 1024 * 1024
            self.send_response(200)
            self.send_header("content-length", str(size))
            self.end_headers()
            chunk = b"B" * 65536
            sent = 0
            while sent < size:
                self.wfile.write(chunk)
                sent += len(chunk)
            return
        body = f"resp:{self.path}".encode()
        self.send_response(200)
        self.send_header("content-length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):
        pass


class TestH2ConcurrentStreaming:
    """VERDICT r2 item 6: h2 streams are serviced CONCURRENTLY (a slow
    stream must not head-of-line block its siblings) and response bodies
    STREAM (a response larger than the old 1 MiB whole-buffer cap must
    arrive intact)."""

    def _stack(self, tmp_path):
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              _DelayEchoUpstream)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        stack = NativeStack(tmp_path, _block_rules())
        stack.proc.kill()
        stack.proc.wait()
        stack.proc = subprocess.Popen(
            [HTTPD, str(stack.port), stack.ring_path, "127.0.0.1",
             str(srv.server_address[1])],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        assert b"listening" in stack.proc.stdout.readline()
        return srv, stack

    def test_slow_stream_does_not_block_fast_sibling(self, tmp_path):
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        from pingoo_tpu.host.h2 import H2UpstreamConnection

        srv, stack = self._stack(tmp_path)
        loop = asyncio.new_event_loop()
        try:
            async def run():
                conn = H2UpstreamConnection("127.0.0.1", stack.port)
                await conn.connect()
                order = []

                async def one(path, tag):
                    st, _, body = await conn.request(
                        "GET", "t.test", path, [("user-agent", "ua")], b"")
                    order.append(tag)
                    return st, body

                # the slow stream FIRST, so sequential servicing would
                # finish it before the fast one
                slow = asyncio.create_task(one("/slow/a", "slow"))
                await asyncio.sleep(0.15)  # slow stream reaches upstream
                fast = asyncio.create_task(one("/fast/b", "fast"))
                (s_st, s_body), (f_st, f_body) = await asyncio.gather(
                    slow, fast)
                await conn.close()
                assert s_st == 200 and b"resp:/slow/a" in s_body
                assert f_st == 200 and b"resp:/fast/b" in f_body
                return order

            order = loop.run_until_complete(asyncio.wait_for(run(), 60))
            assert order[0] == "fast", order  # no head-of-line blocking
        finally:
            loop.close()
            stack.stop()
            srv.shutdown()

    def test_big_response_streams_past_buffer_cap(self, tmp_path):
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        from pingoo_tpu.host.h2 import H2UpstreamConnection

        srv, stack = self._stack(tmp_path)
        loop = asyncio.new_event_loop()
        try:
            async def run():
                conn = H2UpstreamConnection("127.0.0.1", stack.port)
                await conn.connect()
                st, headers, body = await conn.request(
                    "GET", "t.test", "/big", [("user-agent", "ua")], b"")
                await conn.close()
                return st, body

            st, body = loop.run_until_complete(asyncio.wait_for(run(), 120))
            assert st == 200
            assert len(body) == 4 * 1024 * 1024  # > the old 1 MiB cap
            assert body[:4] == b"BBBB" and body[-4:] == b"BBBB"
        finally:
            loop.close()
            stack.stop()
            srv.shutdown()


class TestSidecarGeoEnrichment:
    """The C++ plane enqueues asn=0/country=XX (it has no mmdb decoder);
    the sidecar must fill real geo columns before the verdict so geo/asn
    rules fire for natively fronted traffic (reference resolves geoip in
    the listener, http_listener.rs:143-157)."""

    def test_geo_rule_fires_via_ring(self, tmp_path):
        import ipaddress

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression
        from pingoo_tpu.host.geoip import GeoipDB, MmdbReader, build_mmdb

        mmdb = build_mmdb({
            "203.0.113.0/24": {
                "country": {"iso_code": "ZZ"},
                "autonomous_system_number": 64999,
            },
        })
        geoip = GeoipDB(MmdbReader(mmdb))
        rules = [
            RuleConfig(name="geo", actions=(Action.BLOCK,),
                       expression=compile_expression(
                           'client.country == "ZZ"')),
            RuleConfig(name="asn", actions=(Action.BLOCK,),
                       expression=compile_expression(
                           "client.asn == 64999")),
        ]
        plan = compile_ruleset(rules, {})
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            ip_in = (b"\x00" * 10 + b"\xff\xff"
                     + ipaddress.ip_address("203.0.113.7").packed)
            ip_out = (b"\x00" * 10 + b"\xff\xff"
                      + ipaddress.ip_address("198.51.100.9").packed)
            t_hit = ring.enqueue(method=b"GET", host=b"h", path=b"/",
                                 url=b"/", user_agent=b"ua", ip=ip_in,
                                 port=2000)
            t_miss = ring.enqueue(method=b"GET", host=b"h", path=b"/",
                                  url=b"/", user_agent=b"ua", ip=ip_out,
                                  port=2000)
            sidecar = RingSidecar(ring, plan, {}, max_batch=8,
                                  pipeline_depth=1, geoip=geoip)
            sidecar.run(max_requests=2)
            got = {}
            while True:
                v = ring.poll_verdict()
                if v is None:
                    break
                got[v[0]] = v[1]
            assert got[t_hit] & 3 == 1, got  # ZZ/64999 -> block
            assert got[t_miss] & 3 == 0, got  # not in the mmdb -> none
        finally:
            ring.close()

    def test_no_geoip_keeps_markers(self, tmp_path):
        import ipaddress

        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [RuleConfig(name="geo", actions=(Action.BLOCK,),
                            expression=compile_expression(
                                'client.country == "ZZ"'))]
        plan = compile_ruleset(rules, {})
        ring = Ring(str(tmp_path / "ring"), capacity=64, create=True)
        try:
            ip = (b"\x00" * 10 + b"\xff\xff"
                  + ipaddress.ip_address("203.0.113.7").packed)
            t = ring.enqueue(method=b"GET", host=b"h", path=b"/", url=b"/",
                             user_agent=b"ua", ip=ip, port=2000)
            sidecar = RingSidecar(ring, plan, {}, max_batch=8,
                                  pipeline_depth=1)  # geoip=None
            sidecar.run(max_requests=1)
            v = ring.poll_verdict()
            assert v is not None and v[0] == t and v[1] & 3 == 0
        finally:
            ring.close()


class TestNativePlaneRunner:
    """Production wiring (host/native_plane.py): config in, C++ front
    door + loopback Python plane + sidecar + services republisher out."""

    def _write_config(self, tmp_path, port, up_port):
        import textwrap

        cfg = tmp_path / "pingoo.yml"
        cfg.write_text(textwrap.dedent(f"""
        listeners:
          main:
            address: "http://127.0.0.1:{port}"
        services:
          app:
            http_proxy: ["http://127.0.0.1:{up_port}"]
        rules:
          block-env:
            expression: http_request.path.starts_with("/.env")
            actions: [{{action: block}}]
          block-xss:
            expression: http_request.url.contains("<script")
            actions: [{{action: block}}]
        """))
        return cfg

    def test_end_to_end(self, tmp_path, loop_runner):
        import urllib.request

        from pingoo_tpu.config import load_and_validate
        from pingoo_tpu.host.native_plane import NativePlane

        upstream = http.server.HTTPServer(("127.0.0.1", 0), _Upstream)
        threading.Thread(target=upstream.serve_forever, daemon=True).start()
        port = _free_port()
        config = load_and_validate(str(self._write_config(
            tmp_path, port, upstream.server_address[1])))
        plane = NativePlane(
            config, state_dir=str(tmp_path / "state"), use_device=False,
            enable_docker=False,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "jwks.json"),
            tls_dir=str(tmp_path / "tls"))
        loop_runner.run(plane.start(), timeout=180)
        try:
            def get(path):
                # accept json keeps the metrics scrape on the legacy
                # schema (the default exposition is Prometheus now).
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    headers={"accept": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()
                except (urllib.error.URLError, OSError) as e:
                    # connection-level blips retry like wrong statuses
                    return None, repr(e).encode()

            def get_until(path, want_status, timeout_s=60):
                # Verdicts fail OPEN past their deadline by design; on
                # a heavily loaded host a blocked probe can slip
                # through while a competing compile hogs the core —
                # poll so the test asserts the policy, not the load.
                deadline = time.time() + timeout_s
                while True:
                    status, body = get(path)
                    if status == want_status or time.time() > deadline:
                        return status, body
                    time.sleep(0.5)

            status, body = get_until("/hello", 200)
            assert status == 200 and body == b"up:/hello", (status, body)
            status, _ = get_until("/.env", 403, 30)
            assert status == 403
            status, _ = get_until("/p?x=<script>alert(1)</script>", 403, 30)
            assert status == 403
            # Native metrics surface reachable on the public port.
            status, body = get_until("/__pingoo/metrics", 200, 30)
            assert status == 200
            stats = json.loads(body)
            assert stats["blocked"] >= 2 and stats["verdicts"] >= 3
            assert plane.procs and all(
                p.poll() is None for p in plane.procs)
        finally:
            loop_runner.run(plane.stop(), timeout=60)
        assert all(p.poll() is not None for p in plane.procs)


class TestNativePlaneWiring:
    def test_tcp_listeners_keep_public_address(self):
        import dataclasses

        from pingoo_tpu.config.schema import (Config, ListenerConfig,
                                              ListenerProtocol,
                                              ServiceConfig, Upstream)
        from pingoo_tpu.host.native_plane import _loopback_rebase

        up = Upstream(hostname="127.0.0.1", port=9, tls=False, ip="127.0.0.1")
        config = Config(
            listeners=(
                ListenerConfig(name="web", host="0.0.0.0", port=8080,
                               protocol=ListenerProtocol.HTTP,
                               services=("app",)),
                ListenerConfig(name="db", host="0.0.0.0", port=5432,
                               protocol=ListenerProtocol.TCP,
                               services=("dbsvc",)),
            ),
            services=(
                ServiceConfig(name="app", http_proxy=(up,)),
                ServiceConfig(name="dbsvc", tcp_proxy=(up,)),
            ),
            rules=(), lists=())
        rebased = _loopback_rebase(config)
        by_name = {l.name: l for l in rebased.listeners}
        assert by_name["web"].host == "127.0.0.1"
        # Port 0: the kernel assigns at bind (no pick-then-rebind race);
        # NativePlane reads the real port back after Server.start().
        assert by_name["web"].port == 0
        # TCP listeners are fronted by the C++ plane in tcp-proxy mode
        # (round 5): the Python plane no longer binds them at all.
        assert "db" not in by_name

    def test_tls_and_h2_upstreams_published_natively(self, tmp_path):
        """TLS upstreams ride the native connector (round 4); h2://
        prior-knowledge upstreams are table-marked `h2` and ride the
        native nghttp2 client (round 5) — no loopback detours left for
        proxy upstreams."""
        from pingoo_tpu.config.schema import (Config, ListenerConfig,
                                              ListenerProtocol,
                                              ServiceConfig, Upstream)
        from pingoo_tpu.host.native_plane import NativePlane

        tls_up = Upstream(hostname="backend.test", port=443, tls=True,
                          ip="1.2.3.4")
        h2_up = Upstream(hostname="1.2.3.5", port=8443, tls=False,
                         ip="1.2.3.5", h2=True)
        plain_up = Upstream(hostname="127.0.0.1", port=9, tls=False,
                            ip="127.0.0.1")
        config = Config(
            listeners=(ListenerConfig(
                name="web", host="127.0.0.1", port=_free_port(),
                protocol=ListenerProtocol.HTTP,
                services=("sec", "h2svc", "plain")),),
            services=(ServiceConfig(name="sec", http_proxy=(tls_up,)),
                      ServiceConfig(name="h2svc", http_proxy=(h2_up,)),
                      ServiceConfig(name="plain", http_proxy=(plain_up,))),
            rules=(), lists=())
        plane = NativePlane(config, state_dir=str(tmp_path / "st"),
                            use_device=False)
        plane._listener_services = {"web": ["sec", "h2svc", "plain"]}
        plane.services_paths = {"web": str(tmp_path / "st" / "web.tbl")}
        plane._loopback_ports = {"web": 54321}  # as read back post-bind

        class FakeRegistry:
            def get_upstreams(self, name):
                return {"sec": [tls_up], "h2svc": [h2_up],
                        "plain": [plain_up]}[name]

        plane.server.registry = FakeRegistry()
        os.makedirs(plane.state_dir, exist_ok=True)
        plane._write_services()
        # Parse the table back into {service: [upstream line parts]}.
        table = {}
        current = None
        for line in open(plane.services_paths["web"]).read(
                ).strip().splitlines():
            parts = line.split()
            if parts[0] == "service":
                current = parts[2]
                table[current] = []
            elif parts[0] == "upstream":
                table[current].append(tuple(parts[1:]))
        # TLS upstream: native, with the configured name for SNI/verify.
        assert table["sec"] == [("1.2.3.4", "443", "tls", "backend.test")]
        # h2 prior-knowledge: native nghttp2 client, no loopback hop.
        assert table["h2svc"] == [("1.2.3.5", "8443", "h2")]
        assert table["plain"] == [("127.0.0.1", "9")]


# -- TLS upstream hop (round 4, VERDICT r3 item 2) ---------------------------
# The C++ connector dials `tls`-marked table entries itself: OpenSSL
# client with SNI + mandatory verification against --upstream-ca (or the
# system roots), pooled like plaintext links. Reference semantics:
# http_proxy_service.rs:54-71 (pooled hyper-rustls client, no insecure
# mode; upstream connect/handshake failure -> 502 :192-195).


def _mini_ca():
    """-> (ca_cert_pem, ca_key): a one-off issuing CA."""
    import datetime

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "pingoo-test-ca")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=7))
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(key, hashes.SHA256())
    )
    from cryptography.hazmat.primitives import serialization

    return cert.public_bytes(serialization.Encoding.PEM), key


def _issue(ca_pem, ca_key, sans):
    """CA-signed leaf for `sans` (DNS names or IP literals)."""
    import datetime
    import ipaddress as ipa

    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID

    ca_cert = x509.load_pem_x509_certificate(ca_pem)
    key = ec.generate_private_key(ec.SECP256R1())
    alt = []
    for s in sans:
        try:
            alt.append(x509.IPAddress(ipa.ip_address(s)))
        except ValueError:
            alt.append(x509.DNSName(s))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name(
            [x509.NameAttribute(NameOID.COMMON_NAME, sans[0])]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=7))
        .add_extension(x509.SubjectAlternativeName(alt), critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    key_pem = key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption())
    return cert.public_bytes(serialization.Encoding.PEM), key_pem


def _tls_tagged_upstream(tag, tmp, cert_pem, key_pem, stem):
    cert_path = str(tmp / f"{stem}.pem")
    key_path = str(tmp / f"{stem}.key")
    open(cert_path, "wb").write(cert_pem)
    open(key_path, "wb").write(key_pem)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _TaggedUpstream)
    srv.tag = tag
    srv.delay_s = 0
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    srv.socket = ctx.wrap_socket(srv.socket, server_side=True)
    # Handshake failures from intentionally-mistrusting clients land in
    # handler threads; keep them out of the test log.
    srv.handle_error = lambda *a: None
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class TestTlsUpstreamNative:
    def _routes(self):
        from pingoo_tpu.expr import compile_expression

        return [("api", compile_expression(
                    'http_request.path.starts_with("/api")')),
                ("web", None)]

    def _get(self, port, path):
        payload = (f"GET {path} HTTP/1.1\r\nhost: t.test\r\n"
                   "user-agent: routed/1.0\r\nconnection: close\r\n\r\n")
        return raw_request(port, payload.encode())

    def _get_until(self, port, path, want, tries=25):
        out = b""
        for _ in range(tries):
            out = self._get(port, path)
            if want in out:
                return out
            time.sleep(0.4)
        return out

    def _metrics(self, port):
        out = raw_request(
            port,
            b"GET /__pingoo/metrics HTTP/1.1\r\nhost: t\r\n"
            b"user-agent: m/1.0\r\naccept: application/json\r\n"
            b"connection: close\r\n\r\n")
        return json.loads(out.split(b"\r\n\r\n", 1)[1])

    def test_tls_upstream_proxied_verified_and_pooled(self, tmp_path):
        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["upstream.test"])
        sec = _tls_tagged_upstream("svc-tls", tmp_path, cert, key, "sec")
        web = _tagged_upstream("svc-plain")
        services = [
            ("api", [("127.0.0.1", sec.server_address[1], "upstream.test")]),
            ("web", [("127.0.0.1", web.server_address[1])]),
        ]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services, upstream_ca=ca_path)
        try:
            out = self._get_until(stack.port, "/api/v1", b"svc-tls")
            assert b"svc-tls:/api/v1" in out, out[:300]
            # Keep-alive reuse: the pooled TLS session carries request 2.
            out = self._get(stack.port, "/api/v2")
            assert b"svc-tls:/api/v2" in out, out[:300]
            m = self._metrics(stack.port)
            assert m["upstream_tls_fail"] == 0
            # Plain routing unaffected.
            out = self._get(stack.port, "/index.html")
            assert b"svc-plain:/index.html" in out, out[:300]
        finally:
            stack.stop()
            sec.shutdown()
            web.shutdown()

    def test_tls_upstream_ip_san(self, tmp_path):
        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["127.0.0.1"])
        sec = _tls_tagged_upstream("svc-ip", tmp_path, cert, key, "sec")
        web = _tagged_upstream("svc-plain")
        services = [
            ("api", [("127.0.0.1", sec.server_address[1], "127.0.0.1")]),
            ("web", [("127.0.0.1", web.server_address[1])]),
        ]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services, upstream_ca=ca_path)
        try:
            out = self._get_until(stack.port, "/api/ip", b"svc-ip")
            assert b"svc-ip:/api/ip" in out, out[:300]
        finally:
            stack.stop()
            sec.shutdown()
            web.shutdown()

    def test_tls_upstream_untrusted_cert_rejected(self, tmp_path):
        """An upstream presenting a cert from OUTSIDE the trust bundle
        must never be proxied to: handshake aborts, client gets 502
        (http_proxy_service.rs:192-195), upstream_tls_fail counts it."""
        from pingoo_tpu.host.tlsmgr import generate_self_signed

        ca_pem, _ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = generate_self_signed(["upstream.test"])  # wrong issuer
        sec = _tls_tagged_upstream("svc-evil", tmp_path, cert, key, "sec")
        web = _tagged_upstream("svc-plain")
        services = [
            ("api", [("127.0.0.1", sec.server_address[1], "upstream.test")]),
            ("web", [("127.0.0.1", web.server_address[1])]),
        ]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services, upstream_ca=ca_path)
        try:
            # Warm routing on the healthy service first (early requests
            # fail open to service 0 while the first batch compiles).
            out = self._get_until(stack.port, "/w", b"svc-plain")
            assert b"svc-plain:/w" in out, out[:300]
            out = self._get(stack.port, "/api/secret")
            assert b"502" in out.split(b"\r\n", 1)[0], out[:300]
            assert b"svc-evil" not in out
            m = self._metrics(stack.port)
            assert m["upstream_tls_fail"] >= 1
        finally:
            stack.stop()
            sec.shutdown()
            web.shutdown()

    def test_tls_upstream_name_mismatch_rejected(self, tmp_path):
        """CA-trusted but wrong name: hostname verification must fail
        the hop (rustls verifies the server name the same way)."""
        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["other.test"])
        sec = _tls_tagged_upstream("svc-mismatch", tmp_path, cert, key, "sec")
        web = _tagged_upstream("svc-plain")
        services = [
            ("api", [("127.0.0.1", sec.server_address[1], "upstream.test")]),
            ("web", [("127.0.0.1", web.server_address[1])]),
        ]
        stack = NativeStack(tmp_path, rules=[], routes=self._routes(),
                            services=services, upstream_ca=ca_path)
        try:
            out = self._get_until(stack.port, "/w", b"svc-plain")
            assert b"svc-plain:/w" in out, out[:300]
            out = self._get(stack.port, "/api/secret")
            assert b"502" in out.split(b"\r\n", 1)[0], out[:300]
            m = self._metrics(stack.port)
            assert m["upstream_tls_fail"] >= 1
        finally:
            stack.stop()
            sec.shutdown()
            web.shutdown()

    def test_malformed_tls_line_keeps_last_good_table(self, tmp_path):
        """A hot-reloaded table whose `tls` entry lost its server name
        must be REJECTED (keep last good table), never downgraded to a
        plaintext hop carrying the request in clear."""
        web = _tagged_upstream("svc-good")
        services = [("web", [("127.0.0.1", web.server_address[1])])]
        stack = NativeStack(tmp_path, rules=[],
                            routes=[("web", None)], services=services)
        try:
            out = self._get_until(stack.port, "/a", b"svc-good")
            assert b"svc-good:/a" in out, out[:300]
            time.sleep(1.1)  # distinct mtime second for the reload tick
            with open(stack.services_path, "w") as f:
                f.write("pingoo-services v1\n"
                        "service 0 web\n"
                        f"upstream 127.0.0.1 {web.server_address[1]} tls\n")
            time.sleep(1.5)  # reload tick runs at 1 Hz
            out = self._get(stack.port, "/b")
            assert b"svc-good:/b" in out, out[:300]
        finally:
            stack.stop()
            web.shutdown()


class TestTlsUpstreamTruncation:
    """ADVICE r4: a TLS upstream ending an EOF-delimited body with a
    bare TCP FIN (no close_notify) is indistinguishable from a clean
    end unless the alert is required — an attacker able to inject a FIN
    could truncate responses undetected. The connector must treat
    SSL_ERROR_SYSCALL/ret==0 as an error (rustls: UnexpectedEof): over
    h2 the stream RESETS instead of certifying a short body complete.
    A close_notify-terminated EOF body must still complete."""

    def test_close_notify_completes_bare_fin_resets(self, tmp_path):
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        from pingoo_tpu.expr import compile_expression

        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["upstream.test"])
        cert_path, key_path = str(tmp_path / "u.pem"), str(tmp_path / "u.key")
        open(cert_path, "wb").write(cert)
        open(key_path, "wb").write(key)

        mode = {"clean": True}
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(8)
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        ctx.load_cert_chain(cert_path, key_path)

        def serve():
            while True:
                try:
                    raw, _ = lsock.accept()
                except OSError:
                    return
                try:
                    conn = ctx.wrap_socket(raw, server_side=True)
                    data = b""
                    while b"\r\n\r\n" not in data:
                        ch = conn.recv(65536)
                        if not ch:
                            break
                        data += ch
                    # No content-length: EOF-delimited body (kUntilEof)
                    conn.sendall(b"HTTP/1.1 200 OK\r\n"
                                 b"connection: close\r\n\r\nEOFBODY")
                    if mode["clean"]:
                        try:
                            conn.unwrap()  # sends close_notify
                        except OSError:
                            pass
                        conn.close()
                    else:
                        # FIN without close_notify: detach the raw fd
                        # and close it beneath the TLS layer.
                        os.close(conn.detach())
                except OSError:
                    pass

        threading.Thread(target=serve, daemon=True).start()

        routes = [("api", compile_expression(
                      'http_request.path.starts_with("/api")')),
                  ("web", None)]
        services = [
            ("api", [("127.0.0.1", lsock.getsockname()[1],
                      "upstream.test")]),
            ("web", [("127.0.0.1", 9)]),  # unused
        ]
        stack = NativeStack(tmp_path, rules=[], routes=routes,
                            services=services, upstream_ca=ca_path)
        try:
            # Warm the route (first requests fail open while the first
            # verdict batch compiles).
            out = b""
            for _ in range(25):
                out = raw_request(
                    stack.port,
                    b"GET /api/w HTTP/1.1\r\nhost: t.test\r\n"
                    b"user-agent: ua\r\nconnection: close\r\n\r\n")
                if b"EOFBODY" in out:
                    break
                time.sleep(0.4)
            assert b"EOFBODY" in out, out[:300]

            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def req():
                conn = H2UpstreamConnection("127.0.0.1", stack.port)
                await conn.connect()
                try:
                    return await asyncio.wait_for(
                        conn.request("GET", "t.test", "/api/x",
                                     [("user-agent", "ua")]), 10)
                finally:
                    await conn.close()

            # Clean close_notify: EOF-delimited body certified complete.
            st, _hdrs, body = asyncio.run(req())
            assert st == 200 and body == b"EOFBODY"

            # Bare FIN: the h2 stream must RESET, not end cleanly.
            mode["clean"] = False
            with pytest.raises(ConnectionError, match="reset"):
                asyncio.run(req())
            m = json.loads(raw_request(
                stack.port,
                b"GET /__pingoo/metrics HTTP/1.1\r\nhost: t\r\n"
                b"user-agent: m\r\naccept: application/json\r\n"
                b"connection: close\r\n\r\n"
            ).split(b"\r\n\r\n", 1)[1])
            assert m["upstream_tls_fail"] == 0  # handshakes all fine
        finally:
            stack.stop()
            lsock.close()


class TestPerListenerServiceSets:
    """VERDICT r4 item 2: two HTTP listeners front DIFFERENT service
    sets natively — each listener's verdict route field indexes its OWN
    table (reference: per-listener service binding, config.rs:241-253 +
    selection loop http_listener.rs:266-270)."""

    def test_two_listeners_different_service_sets(self, tmp_path,
                                                  loop_runner):
        import textwrap
        import urllib.request

        from pingoo_tpu.config import load_and_validate
        from pingoo_tpu.host.native_plane import NativePlane

        api = _tagged_upstream("svc-api")
        web = _tagged_upstream("svc-web")
        admin = _tagged_upstream("svc-admin")
        port_a, port_b = _free_port(), _free_port()
        cfg = tmp_path / "pingoo.yml"
        cfg.write_text(textwrap.dedent(f"""
        listeners:
          edge:
            address: "http://127.0.0.1:{port_a}"
            services: [api, web]
          back:
            address: "http://127.0.0.1:{port_b}"
            services: [admin, web]
        services:
          api:
            http_proxy: ["http://127.0.0.1:{api.server_address[1]}"]
            route: http_request.path.starts_with("/api")
          admin:
            http_proxy: ["http://127.0.0.1:{admin.server_address[1]}"]
            route: http_request.path.starts_with("/admin")
          web:
            http_proxy: ["http://127.0.0.1:{web.server_address[1]}"]
        rules: {{}}
        """))
        config = load_and_validate(str(cfg))
        plane = NativePlane(
            config, state_dir=str(tmp_path / "state"), use_device=False,
            enable_docker=False,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "jwks.json"),
            tls_dir=str(tmp_path / "tls"))
        loop_runner.run(plane.start(), timeout=180)
        try:
            def get(port, path):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    headers={"user-agent": "plst/1.0"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            # Warm both listeners until routed verdicts flow (early
            # requests fail open to service 0 during first compile).
            deadline = time.time() + 60
            while time.time() < deadline:
                sa, ba = get(port_a, "/x")[1], get(port_b, "/x")[1]
                if b"svc-web" in sa and b"svc-web" in ba:
                    break
                time.sleep(0.5)
            # edge routes /api natively to svc-api; back has no api
            # service, so /api falls through to its catch-all web.
            assert b"svc-api:/api/v1" in get(port_a, "/api/v1")[1]
            assert b"svc-web:/api/v1" in get(port_b, "/api/v1")[1]
            # back routes /admin to svc-admin; edge falls to web.
            assert b"svc-admin:/admin/p" in get(port_b, "/admin/p")[1]
            assert b"svc-web:/admin/p" in get(port_a, "/admin/p")[1]
            # Each listener wrote its OWN table file.
            assert set(plane.services_paths) == {"edge", "back"}
            tbl_edge = open(plane.services_paths["edge"]).read()
            tbl_back = open(plane.services_paths["back"]).read()
            assert "service 0 api" in tbl_edge
            assert "service 0 admin" in tbl_back
        finally:
            loop_runner.run(plane.stop(), timeout=60)


def _tcp_echo_upstream(prefix=b"echo:"):
    """Threaded echo server replying `prefix + data` per recv; the
    listen socket is returned (close() stops the accept loop)."""
    ls = socket.socket()
    ls.bind(("127.0.0.1", 0))
    ls.listen(8)

    def serve():
        while True:
            try:
                conn, _ = ls.accept()
            except OSError:
                return

            def pump(conn=conn):
                while True:
                    d = conn.recv(4096)
                    if not d:
                        break
                    conn.sendall(prefix + d)
                conn.close()

            threading.Thread(target=pump, daemon=True).start()

    threading.Thread(target=serve, daemon=True).start()
    return ls


class TestNativeTcpFronting:
    """VERDICT r4 item 3: TCP(+TLS) listeners are fronted by the C++
    plane (tcp-proxy mode — accept, optional TLS terminate, random
    upstream with retries, bidirectional splice; reference
    tcp_listener.rs:39-70, tcp_tls_listener.rs:42-79,
    tcp_proxy_service.rs:30-84). Python is control plane only."""

    def _echo_upstream(self):
        return _tcp_echo_upstream(b"echo:")

    def _config(self, tmp_path, proto, tcp_port, http_port, up_port,
                echo_port):
        import textwrap

        cfg = tmp_path / "pingoo.yml"
        cfg.write_text(textwrap.dedent(f"""
        listeners:
          web:
            address: "http://127.0.0.1:{http_port}"
            services: [app]
          db:
            address: "{proto}://127.0.0.1:{tcp_port}"
            services: [dbsvc]
        services:
          app:
            http_proxy: ["http://127.0.0.1:{up_port}"]
          dbsvc:
            tcp_proxy: ["tcp://127.0.0.1:{echo_port}"]
        rules: {{}}
        """))
        return cfg

    def _boot(self, tmp_path, loop_runner, proto):
        from pingoo_tpu.config import load_and_validate
        from pingoo_tpu.host.native_plane import NativePlane

        echo = self._echo_upstream()
        up = _tagged_upstream("svc-app")
        tcp_port, http_port = _free_port(), _free_port()
        config = load_and_validate(str(self._config(
            tmp_path, proto, tcp_port, http_port,
            up.server_address[1], echo.getsockname()[1])))
        plane = NativePlane(
            config, state_dir=str(tmp_path / "state"), use_device=False,
            enable_docker=False,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "jwks.json"),
            tls_dir=str(tmp_path / "tls"))
        loop_runner.run(plane.start(), timeout=180)
        return plane, echo, up, tcp_port

    def test_tcp_proxied_natively(self, tmp_path, loop_runner):
        plane, echo, up, tcp_port = self._boot(tmp_path, loop_runner,
                                               "tcp")
        try:
            # The Python plane binds NO tcp server: native carries it.
            assert plane.server.tcp_servers == []
            c = socket.create_connection(("127.0.0.1", tcp_port),
                                         timeout=10)
            c.settimeout(10)
            c.sendall(b"SELECT 1")
            assert c.recv(100) == b"echo:SELECT 1"
            c.sendall(b"more")
            assert c.recv(100) == b"echo:more"
            # half-close propagates; reverse direction stays open
            c.shutdown(socket.SHUT_WR)
            assert c.recv(100) == b""
            c.close()
        finally:
            loop_runner.run(plane.stop(), timeout=60)
            echo.close()
            up.shutdown()

    def test_tcp_tls_terminated_natively(self, tmp_path, loop_runner):
        plane, echo, up, tcp_port = self._boot(tmp_path, loop_runner,
                                               "tcp+tls")
        try:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            # the plane generates a self-signed `*` default cert on
            # first boot (tls_manager.rs:193-231 semantics)
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
            raw = socket.create_connection(("127.0.0.1", tcp_port),
                                           timeout=10)
            c = ctx.wrap_socket(raw, server_hostname="db.test")
            c.settimeout(10)
            c.sendall(b"tls-bytes")
            assert c.recv(100) == b"echo:tls-bytes"
            c.close()
        finally:
            loop_runner.run(plane.stop(), timeout=60)
            echo.close()
            up.shutdown()

    def test_tcp_connect_retries_ride_through_outage(self, tmp_path):
        """A transient upstream outage at connect time must be ridden
        through by the retry ladder (reference tcp_proxy_service.rs:
        30-84 retries with delays), not surfaced as an instant drop."""
        from pingoo_tpu.native_ring import Ring, write_services_file

        # reserve a port, nothing listening yet
        hold = socket.socket()
        hold.bind(("127.0.0.1", 0))
        up_port = hold.getsockname()[1]
        hold.close()

        tbl = str(tmp_path / "svc.tbl")
        write_services_file(tbl, [("db", [("127.0.0.1", up_port)])])
        ring = Ring(str(tmp_path / "r"), capacity=64, create=True)
        port = _free_port()
        env = dict(os.environ)
        env["PINGOO_TCP_RETRIES"] = "8"  # span >5 sweep seconds
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "r"), "127.0.0.1", "9",
             "--services", tbl, "--tcp-proxy"],
            stdout=subprocess.PIPE, env=env)
        assert b"listening" in proc.stdout.readline()
        try:
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            c.settimeout(20)
            c.sendall(b"early")  # buffered while the proxy retries

            def bring_up():
                time.sleep(1.5)
                ls = socket.socket()
                ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                ls.bind(("127.0.0.1", up_port))
                ls.listen(4)
                conn, _ = ls.accept()
                d = conn.recv(100)
                conn.sendall(b"late-echo:" + d)
                conn.close()
                ls.close()

            t = threading.Thread(target=bring_up, daemon=True)
            t.start()
            assert c.recv(100) == b"late-echo:early"
            c.close()
            t.join(timeout=10)
        finally:
            proc.kill()
            proc.wait()
            ring.close()


class TestH2UpstreamNative:
    """VERDICT r4 item 7: h2 upstream hops ride the native connector —
    cleartext prior-knowledge for table-marked `h2` targets, ALPN for
    TLS targets (reference hyper client, http_proxy_service.rs:54-71).
    The second httpd in each chain is itself the h2 upstream server."""

    def _mk_httpd(self, tmp_path, tag, port, upstream_port, extra=()):
        ring_path = str(tmp_path / f"ring_{tag}")
        ring = Ring(ring_path, capacity=256, create=True)
        drain = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
            stdout=subprocess.PIPE)
        assert b"draining" in drain.stdout.readline()
        h = subprocess.Popen(
            [HTTPD, str(port), ring_path, "127.0.0.1",
             str(upstream_port)] + list(extra), stdout=subprocess.PIPE)
        assert b"listening" in h.stdout.readline()
        return ring, drain, h

    def test_h2c_prior_knowledge_upstream_pooled(self, tmp_path):
        from pingoo_tpu.native_ring import H2

        class _PostEcho(_TaggedUpstream):
            def do_POST(self):
                n = int(self.headers.get("content-length", 0))
                got = self.rfile.read(n)
                body = f"post:{len(got)}:{got[:8].decode()}".encode()
                self.send_response(200)
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        pong = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _PostEcho)
        pong.tag = "svc-pong"
        pong.delay_s = 0
        threading.Thread(target=pong.serve_forever, daemon=True).start()
        pa, pb = _free_port(), _free_port()
        cleanup = []
        try:
            cleanup.append(self._mk_httpd(
                tmp_path, "b", pb, pong.server_address[1]))
            tbl = str(tmp_path / "svc.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, H2)])])
            cleanup.append(self._mk_httpd(
                tmp_path, "a", pa, 9, ("--services", tbl)))
            # two keep-alive h1 requests: the second rides the POOLED
            # h2 session (same upstream connection)
            out1 = raw_request(
                pa, b"GET /h2c1 HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"connection: close\r\n\r\n")
            assert b"svc-pong:/h2c1" in out1, out1[:300]
            out2 = raw_request(
                pa, b"GET /h2c2 HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"connection: close\r\n\r\n")
            assert b"svc-pong:/h2c2" in out2, out2[:300]
            # POST body must be re-framed as h2 DATA correctly
            body = b"x" * 5000
            out3 = raw_request(
                pa, b"POST /p HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"content-length: 5000\r\nconnection: close\r\n\r\n"
                    + body)
            assert b"post:5000:xxxxxxxx" in out3, out3[:300]
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            pong.shutdown()

    def test_alpn_h2_tls_upstream(self, tmp_path):
        """A TLS upstream that negotiates h2 via ALPN must be spoken to
        in h2 — transparently, from the same `tls` table entry."""
        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["upstream.test"])
        tls_dir = tmp_path / "btls"
        tls_dir.mkdir()
        (tls_dir / "upstream.test.pem").write_bytes(cert)
        (tls_dir / "upstream.test.key").write_bytes(key)

        pong = _tagged_upstream("svc-pong")
        pa, pb = _free_port(), _free_port()
        cleanup = []
        try:
            # B terminates TLS and ANSWERS h2 when ALPN picks it
            cleanup.append(self._mk_httpd(
                tmp_path, "tb", pb, pong.server_address[1],
                ("--tls-dir", str(tls_dir))))
            tbl = str(tmp_path / "svc_tls.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, "upstream.test")])])
            cleanup.append(self._mk_httpd(
                tmp_path, "ta", pa, 9,
                ("--services", tbl, "--upstream-ca", ca_path)))
            out = raw_request(
                pa, b"GET /alpn1 HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"connection: close\r\n\r\n")
            assert b"svc-pong:/alpn1" in out, out[:300]
            out = raw_request(  # pooled h2-over-TLS session reuse
                pa, b"GET /alpn2 HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"connection: close\r\n\r\n")
            assert b"svc-pong:/alpn2" in out, out[:300]
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            pong.shutdown()

    def test_h2_downstream_over_h2_upstream(self, tmp_path):
        """h2 client -> native plane -> h2c upstream: both hops h2."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        from pingoo_tpu.native_ring import H2

        pong = _tagged_upstream("svc-pong")
        pa, pb = _free_port(), _free_port()
        cleanup = []
        try:
            cleanup.append(self._mk_httpd(
                tmp_path, "db", pb, pong.server_address[1]))
            tbl = str(tmp_path / "svc_d.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, H2)])])
            cleanup.append(self._mk_httpd(
                tmp_path, "da", pa, 9, ("--services", tbl)))
            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", pa)
                await conn.connect()
                try:
                    r1 = await asyncio.wait_for(conn.request(
                        "GET", "t", "/d1", [("user-agent", "u")]), 10)
                    r2 = await asyncio.wait_for(conn.request(
                        "GET", "t", "/d2", [("user-agent", "u")]), 10)
                    return r1, r2
                finally:
                    await conn.close()

            (s1, _h1, b1), (s2, _h2, b2) = asyncio.run(flow())
            assert s1 == 200 and b1 == b"svc-pong:/d1", (s1, b1)
            assert s2 == 200 and b2 == b"svc-pong:/d2", (s2, b2)
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            pong.shutdown()


class TestUpgradePinsH1OnTls:
    """An Upgrade (WebSocket) request to a TLS upstream must NOT offer
    h2 in ALPN — a 101 tunnel cannot ride an h2 hop, and an h2-capable
    upstream would otherwise be negotiated into one (regression guard
    for the round-5 ALPN offer)."""

    def test_ws_upgrade_through_h2_capable_tls_upstream(self, tmp_path):
        ca_pem, ca_key = _mini_ca()
        ca_path = str(tmp_path / "ca.pem")
        open(ca_path, "wb").write(ca_pem)
        cert, key = _issue(ca_pem, ca_key, ["upstream.test"])
        tls_dir = tmp_path / "wtls"
        tls_dir.mkdir()
        (tls_dir / "upstream.test.pem").write_bytes(cert)
        (tls_dir / "upstream.test.key").write_bytes(key)

        ws = _ws_echo_upstream()
        pa, pb = _free_port(), _free_port()
        cleanup = []
        mk = TestH2UpstreamNative()._mk_httpd
        try:
            # B: TLS edge that PREFERS h2 in ALPN, forwards upgrades h1
            cleanup.append(mk(tmp_path, "wb", pb, ws.getsockname()[1],
                              ("--tls-dir", str(tls_dir))))
            tbl = str(tmp_path / "ws.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, "upstream.test")])])
            cleanup.append(mk(tmp_path, "wa", pa, 9,
                              ("--services", tbl,
                               "--upstream-ca", ca_path)))
            # Plain request first: negotiates h2 upstream (the pool now
            # holds an h2 session for this target).
            out = raw_request(
                pa, b"GET /warm HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                    b"connection: close\r\n\r\n")
            assert b"101" not in out.split(b"\r\n", 1)[0]
            # The upgrade must still tunnel: a FRESH h1-pinned TLS
            # connection is dialed even though the pool has h2.
            c = socket.create_connection(("127.0.0.1", pa), timeout=10)
            c.sendall(b"GET /chat HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n"
                      b"connection: Upgrade\r\nupgrade: websocket\r\n"
                      b"sec-websocket-key: dGhlIHNhbXBsZSBub25jZQ==\r\n"
                      b"sec-websocket-version: 13\r\n\r\n")
            head = b""
            c.settimeout(10)
            while b"\r\n\r\n" not in head:
                ch = c.recv(4096)
                if not ch:
                    break
                head += ch
            assert head.startswith(b"HTTP/1.1 101"), head[:200]
            c.sendall(b"\x81\x05hello")
            got = head.partition(b"\r\n\r\n")[2]
            while len(got) < 7:
                got += c.recv(4096)
            assert got == b"\x81\x05hello", got
            c.close()
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            ws.close()


class TestNativeStaticServing:
    """VERDICT r4 item 8: static sites served from the data-plane
    binary (reference http_static_site_service.rs:83-257 semantics:
    GET/HEAD only, traversal guard, index.html, .html prettify,
    SHA256 ETag + If-None-Match 304, 500KB cache limit); files past
    the cache limit proxy to the service's upstream list."""

    def _site(self, tmp_path):
        root = tmp_path / "site"
        (root / "sub").mkdir(parents=True)
        (root / "index.html").write_text("<h1>home</h1>")
        (root / "page.html").write_text("<h1>page</h1>")
        (root / "app.js").write_text("console.log(1)")
        (root / "sub" / "index.html").write_text("<h1>sub</h1>")
        (root / "big.bin").write_bytes(b"B" * 600_000)  # > 500 KB
        return root

    def _stack(self, tmp_path, root):
        fallback = _tagged_upstream("svc-stream")
        ring_path = str(tmp_path / "sring")
        ring = Ring(ring_path, capacity=256, create=True)
        drain = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
            stdout=subprocess.PIPE)
        assert b"draining" in drain.stdout.readline()
        tbl = str(tmp_path / "static.tbl")
        native_ring.write_services_file(
            tbl, [("site", [("127.0.0.1", fallback.server_address[1])],
                   str(root))])
        port = _free_port()
        h = subprocess.Popen(
            [HTTPD, str(port), ring_path, "127.0.0.1", "9",
             "--services", tbl], stdout=subprocess.PIPE)
        assert b"listening" in h.stdout.readline()
        return port, (ring, drain, h, fallback)

    def _req(self, port, payload):
        return raw_request(port, payload)

    def test_static_semantics_native(self, tmp_path):
        root = self._site(tmp_path)
        port, cleanup = self._stack(tmp_path, root)
        try:
            def get(path, extra=b"", method=b"GET"):
                return self._req(
                    port, method + b" " + path +
                    b" HTTP/1.1\r\nhost: t\r\nuser-agent: u\r\n" + extra +
                    b"connection: close\r\n\r\n")

            out = get(b"/")
            assert b"200" in out.split(b"\r\n")[0] and b"<h1>home</h1>" in out
            assert b"content-type: text/html" in out
            etag = [ln for ln in out.split(b"\r\n")
                    if ln.startswith(b"etag:")][0].split(b" ", 1)[1]
            # If-None-Match -> 304, no body
            out = get(b"/", b"if-none-match: " + etag + b"\r\n")
            assert b"304" in out.split(b"\r\n")[0], out[:200]
            assert b"<h1>" not in out
            # prettify: /page -> page.html
            out = get(b"/page")
            assert b"<h1>page</h1>" in out
            # directory -> index.html
            out = get(b"/sub/")
            assert b"<h1>sub</h1>" in out
            # mime by extension
            out = get(b"/app.js")
            assert b"content-type: text/javascript" in out
            # missing with extension -> 404
            out = get(b"/nope.css")
            assert b"404" in out.split(b"\r\n")[0]
            # traversal -> 404 (never escapes the root)
            out = get(b"/../secret")
            assert b"404" in out.split(b"\r\n")[0]
            # POST -> 405 (reference: GET/HEAD only)
            out = get(b"/", method=b"POST")
            assert b"405" in out.split(b"\r\n")[0]
            # HEAD: full content-length, no body
            out = get(b"/", method=b"HEAD")
            assert b"content-length: 13" in out and b"<h1>" not in out
            # oversized file -> proxied to the upstream list
            out = get(b"/big.bin")
            assert b"svc-stream:/big.bin" in out, out[:200]
        finally:
            ring, drain, h, fb = cleanup
            drain.kill()
            h.kill()
            ring.close()
            fb.shutdown()

    def test_static_native_in_plane(self, tmp_path, loop_runner):
        """Full NativePlane: a static config service is served from the
        C++ binary (policy still enforced by the verdict path)."""
        import textwrap
        import urllib.request

        from pingoo_tpu.config import load_and_validate
        from pingoo_tpu.host.native_plane import NativePlane

        root = self._site(tmp_path)
        port = _free_port()
        cfg = tmp_path / "pingoo.yml"
        cfg.write_text(textwrap.dedent(f"""
        listeners:
          web:
            address: "http://127.0.0.1:{port}"
        services:
          site:
            static: {{root: "{root}"}}
        rules:
          blk:
            expression: http_request.path.contains("blocked")
            actions: [{{action: block}}]
        """))
        config = load_and_validate(str(cfg))
        plane = NativePlane(
            config, state_dir=str(tmp_path / "state"), use_device=False,
            enable_docker=False,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "jwks.json"),
            tls_dir=str(tmp_path / "tls"))
        loop_runner.run(plane.start(), timeout=180)
        try:
            def get(path):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    headers={"user-agent": "st/1.0"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            deadline = time.time() + 60
            status, body = None, b""
            while time.time() < deadline:
                status, body = get("/page")
                if status == 200 and b"<h1>page</h1>" in body:
                    break
                time.sleep(0.5)
            assert status == 200 and b"<h1>page</h1>" in body, (status, body)
            # the published table carries the static root
            tbl = open(plane.services_paths["web"]).read()
            assert f"static {root}" in tbl
            # WAF still applies before static dispatch
            status, _ = get("/blocked.html")
            assert status == 403
            # oversized files stream via the control plane
            status, body = get("/big.bin")
            assert status == 200 and len(body) == 600_000
        finally:
            loop_runner.run(plane.stop(), timeout=60)

    def test_static_served_on_h2(self, tmp_path):
        """The h2 downstream path serves static responses natively too
        (reference: same service behind hyper's auto h1/h2 builder)."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        root = self._site(tmp_path)
        port, cleanup = self._stack(tmp_path, root)
        try:
            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    r1 = await asyncio.wait_for(conn.request(
                        "GET", "t", "/page", [("user-agent", "u")]), 10)
                    etag = dict(r1[1])["etag"]
                    r2 = await asyncio.wait_for(conn.request(
                        "GET", "t", "/page",
                        [("user-agent", "u"),
                         ("if-none-match", etag)]), 10)
                    return r1, r2
                finally:
                    await conn.close()

            (s1, h1, b1), (s2, _h2, b2) = asyncio.run(flow())
            assert s1 == 200 and b1 == b"<h1>page</h1>", (s1, b1)
            assert s2 == 304 and b2 == b"", (s2, b2)
        finally:
            ring, drain, h, fb = cleanup
            drain.kill()
            h.kill()
            ring.close()
            fb.shutdown()


class TestTcpUpstreamHalfClose:
    """tcp-proxy mode: an upstream that FINs its send side while still
    reading must get the FIN propagated to the client WITHOUT tearing
    down the client->upstream direction (copy_bidirectional semantics,
    tcp_proxy_service.rs:74-82)."""

    def test_upstream_fin_keeps_client_to_upstream_alive(self, tmp_path):
        from pingoo_tpu.native_ring import Ring, write_services_file

        received = []
        done = threading.Event()
        ls = socket.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(4)

        def serve():
            conn, _ = ls.accept()
            conn.sendall(b"greeting")       # server speaks first...
            conn.shutdown(socket.SHUT_WR)   # ...then FINs its send side
            while True:                     # but KEEPS reading
                d = conn.recv(4096)
                if not d:
                    break
                received.append(d)
            conn.close()
            done.set()

        threading.Thread(target=serve, daemon=True).start()

        tbl = str(tmp_path / "svc.tbl")
        write_services_file(
            tbl, [("db", [("127.0.0.1", ls.getsockname()[1])])])
        ring = Ring(str(tmp_path / "r"), capacity=64, create=True)
        port = _free_port()
        proc = subprocess.Popen(
            [HTTPD, str(port), str(tmp_path / "r"), "127.0.0.1", "9",
             "--services", tbl, "--tcp-proxy"], stdout=subprocess.PIPE)
        assert b"listening" in proc.stdout.readline()
        try:
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            c.settimeout(10)
            assert c.recv(100) == b"greeting"
            assert c.recv(100) == b""  # upstream FIN propagated
            # the reverse direction must still deliver bytes
            c.sendall(b"late-upload")
            c.shutdown(socket.SHUT_WR)
            assert done.wait(10)
            assert b"".join(received) == b"late-upload", received
            c.close()
        finally:
            proc.kill()
            proc.wait()
            ring.close()
            ls.close()


class TestH2UpstreamConcurrency:
    """Concurrent h2 downstream streams over a pooled h2c upstream:
    each stream opens (or reuses) its own upstream h2 session — mixed
    with h1 clients hammering the same pool. Exercises pool handoff,
    GOAWAY-free reuse, and session ownership transfer under load."""

    def test_mixed_h1_h2_traffic_over_h2c_upstream(self, tmp_path):
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        from pingoo_tpu.native_ring import H2

        pong = _tagged_upstream("svc-pong")
        pa, pb = _free_port(), _free_port()
        mk = TestH2UpstreamNative()._mk_httpd
        cleanup = []
        try:
            cleanup.append(mk(tmp_path, "cb", pb, pong.server_address[1]))
            tbl = str(tmp_path / "svc_c.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, H2)])])
            cleanup.append(mk(tmp_path, "ca", pa, 9, ("--services", tbl)))

            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def h2_batch(n):
                conn = H2UpstreamConnection("127.0.0.1", pa)
                await conn.connect()
                try:
                    outs = await asyncio.gather(*[
                        asyncio.wait_for(conn.request(
                            "GET", "t", f"/s{i}", [("user-agent", "u")]),
                            20)
                        for i in range(n)])
                    return outs
                finally:
                    await conn.close()

            h1_results = []

            def h1_hammer(k):
                for i in range(k):
                    out = raw_request(
                        pa, f"GET /h1-{i} HTTP/1.1\r\nhost: t\r\n"
                            f"user-agent: u\r\nconnection: close"
                            f"\r\n\r\n".encode())
                    h1_results.append(b"svc-pong:/h1-" + str(i).encode()
                                      in out)

            t = threading.Thread(target=h1_hammer, args=(30,))
            t.start()
            outs = asyncio.run(h2_batch(24))
            t.join(timeout=60)
            for i, (st, _h, body) in enumerate(outs):
                assert st == 200 and body == f"svc-pong:/s{i}".encode(), \
                    (i, st, body)
            assert len(h1_results) == 30 and all(h1_results)
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            pong.shutdown()


class TestH2UpstreamLargeUpload:
    """A POST bigger than the h2 LINK's body cap: bytes past the cap
    stay in inbuf and MUST be re-pumped when the upstream's
    WINDOW_UPDATEs drain the link (round-5 fix: the client may be done
    sending, so upstream events drive the pump). The front proxy runs
    with PINGOO_MAX_BUFFER=64KB so a 512KB upload exercises the
    stranded-bytes path while staying under the h2 SERVER side's
    buffered-body cap (streamed h2 request bodies are the known
    remaining delta vs hyper)."""

    def test_post_past_link_cap_completes(self, tmp_path):
        from pingoo_tpu.native_ring import H2

        class _BigPost(_TaggedUpstream):
            def do_POST(self):
                n = int(self.headers.get("content-length", 0))
                remaining, total = n, 0
                while remaining:
                    chunk = self.rfile.read(min(65536, remaining))
                    if not chunk:
                        break
                    total += len(chunk)
                    remaining -= len(chunk)
                body = f"got:{total}".encode()
                self.send_response(200)
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        pong = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _BigPost)
        pong.tag = "big"
        pong.delay_s = 0
        threading.Thread(target=pong.serve_forever, daemon=True).start()
        pa, pb = _free_port(), _free_port()
        mk = TestH2UpstreamNative()._mk_httpd
        cleanup = []
        try:
            cleanup.append(mk(tmp_path, "bb", pb, pong.server_address[1]))
            tbl = str(tmp_path / "svc_big.tbl")
            native_ring.write_services_file(
                tbl, [("app", [("127.0.0.1", pb, H2)])])
            env = dict(os.environ)
            env["PINGOO_MAX_BUFFER"] = "65536"
            ring_path = str(tmp_path / "ring_ba")
            ring = Ring(ring_path, capacity=256, create=True)
            drain = subprocess.Popen(
                [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
                stdout=subprocess.PIPE)
            assert b"draining" in drain.stdout.readline()
            h = subprocess.Popen(
                [HTTPD, str(pa), ring_path, "127.0.0.1", "9",
                 "--services", tbl], stdout=subprocess.PIPE, env=env)
            assert b"listening" in h.stdout.readline()
            cleanup.append((ring, drain, h))
            n = 512 * 1024
            body = b"z" * n
            c = socket.create_connection(("127.0.0.1", pa), timeout=30)
            c.sendall((f"POST /up HTTP/1.1\r\nhost: t\r\nuser-agent: u"
                       f"\r\ncontent-length: {n}\r\nconnection: close"
                       f"\r\n\r\n").encode())
            c.sendall(body)
            c.settimeout(60)
            data = b""
            while True:
                try:
                    ch = c.recv(65536)
                except socket.timeout:
                    break
                if not ch:
                    break
                data += ch
            c.close()
            assert f"got:{n}".encode() in data, data[:300]
        finally:
            for ring, drain, h in cleanup:
                drain.kill()
                h.kill()
                ring.close()
            pong.shutdown()

    def test_h2_downstream_body_past_cap_streams_through(self, tmp_path):
        """Round 5: h2 DOWNSTREAM request bodies STREAM to the upstream
        (dispatch at END_HEADERS) — a body far past the buffering cap
        completes as long as the upstream keeps up, like hyper."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")

        class _Count(_TaggedUpstream):
            def do_POST(self):
                n = int(self.headers.get("content-length", 0))
                total, remaining = 0, n
                while remaining:
                    ch = self.rfile.read(min(65536, remaining))
                    if not ch:
                        break
                    total += len(ch)
                    remaining -= len(ch)
                body = f"streamed:{total}".encode()
                self.send_response(200)
                self.send_header("content-length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        pong = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Count)
        pong.tag = "cnt"
        pong.delay_s = 0
        threading.Thread(target=pong.serve_forever, daemon=True).start()
        port = _free_port()
        ring_path = str(tmp_path / "ring_ov")
        ring = Ring(ring_path, capacity=256, create=True)
        drain = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
            stdout=subprocess.PIPE)
        assert b"draining" in drain.stdout.readline()
        env = dict(os.environ)
        env["PINGOO_MAX_BUFFER"] = "65536"
        h = subprocess.Popen(
            [HTTPD, str(port), ring_path, "127.0.0.1",
             str(pong.server_address[1])], stdout=subprocess.PIPE, env=env)
        assert b"listening" in h.stdout.readline()
        try:
            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    big = b"y" * (512 * 1024)  # 8x the buffering cap
                    r1 = await asyncio.wait_for(conn.request(
                        "POST", "t", "/up", [("user-agent", "u")],
                        big), 30)
                    r2 = await asyncio.wait_for(conn.request(
                        "GET", "t", "/after", [("user-agent", "u")]), 15)
                    return r1, r2
                finally:
                    await conn.close()

            (s1, _h1, b1), (s2, _h2, b2) = asyncio.run(flow())
            assert s1 == 200 and b1 == b"streamed:524288", (s1, b1)
            assert s2 == 200 and b2 == b"cnt:/after", (s2, b2)
        finally:
            drain.kill()
            h.kill()
            ring.close()
            pong.shutdown()

    def test_h2_body_to_stalled_upstream_bounded(self, tmp_path):
        """A STALLED upstream bounds a streamed h2 body at the cap: the
        stream errors (reset) instead of buffering without limit, and
        the worker survives."""
        from pingoo_tpu.host import h2 as h2mod

        if not h2mod.available():
            pytest.skip("libnghttp2 unavailable")
        # upstream that accepts and never reads
        ls = socket.socket()
        ls.bind(("127.0.0.1", 0))
        ls.listen(4)
        held = []

        def hold():
            while True:
                try:
                    conn, _ = ls.accept()
                except OSError:
                    return
                held.append(conn)  # never read

        threading.Thread(target=hold, daemon=True).start()
        port = _free_port()
        ring_path = str(tmp_path / "ring_st")
        ring = Ring(ring_path, capacity=256, create=True)
        drain = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
            stdout=subprocess.PIPE)
        assert b"draining" in drain.stdout.readline()
        env = dict(os.environ)
        env["PINGOO_MAX_BUFFER"] = "65536"
        h = subprocess.Popen(
            [HTTPD, str(port), ring_path, "127.0.0.1",
             str(ls.getsockname()[1])], stdout=subprocess.PIPE, env=env)
        assert b"listening" in h.stdout.readline()
        try:
            from pingoo_tpu.host.h2 import H2UpstreamConnection

            async def flow():
                conn = H2UpstreamConnection("127.0.0.1", port)
                await conn.connect()
                try:
                    big = b"y" * (1024 * 1024)
                    try:
                        await asyncio.wait_for(conn.request(
                            "POST", "t", "/up", [("user-agent", "u")],
                            big), 20)
                        return True
                    except (ConnectionError, OSError,
                            asyncio.TimeoutError):
                        return False
                finally:
                    await conn.close()

            completed = asyncio.run(flow())
            assert not completed  # bounded: reset, not buffered forever
            assert h.poll() is None  # worker alive
        finally:
            drain.kill()
            h.kill()
            ring.close()
            ls.close()
            for s in held:
                s.close()

    def test_trailers_end_the_streamed_body(self, tmp_path):
        """An h2 request whose body ends with TRAILERS (HEADERS frame
        carrying END_STREAM) must finish the upstream body — the
        pre-round-5 code only ended bodies on DATA+END_STREAM."""
        got = {}
        done = threading.Event()

        class _Cap(_TaggedUpstream):
            def do_POST(self):
                n = int(self.headers.get("content-length", 0) or 0)
                if n:
                    body = self.rfile.read(n)
                else:
                    # chunked from the proxy (no client content-length)
                    body = b""
                    while True:
                        line = self.rfile.readline().strip()
                        size = int(line, 16)
                        if size == 0:
                            self.rfile.readline()
                            break
                        body += self.rfile.read(size)
                        self.rfile.readline()
                got["body"] = body
                done.set()
                out = b"ok"
                self.send_response(200)
                self.send_header("content-length", "2")
                self.end_headers()
                self.wfile.write(out)

        pong = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _Cap)
        pong.tag = "cap"
        pong.delay_s = 0
        threading.Thread(target=pong.serve_forever, daemon=True).start()
        port = _free_port()
        ring_path = str(tmp_path / "ring_tr")
        ring = Ring(ring_path, capacity=256, create=True)
        drain = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"), ring_path],
            stdout=subprocess.PIPE)
        assert b"draining" in drain.stdout.readline()
        h = subprocess.Popen(
            [HTTPD, str(port), ring_path, "127.0.0.1",
             str(pong.server_address[1])], stdout=subprocess.PIPE)
        assert b"listening" in h.stdout.readline()

        def hp(name, value):  # HPACK literal w/o indexing, new name
            return (b"\x00" + bytes([len(name)]) + name
                    + bytes([len(value)]) + value)

        def frame(ftype, flags, sid, payload):
            return (len(payload).to_bytes(3, "big") + bytes([ftype, flags])
                    + sid.to_bytes(4, "big") + payload)

        try:
            c = socket.create_connection(("127.0.0.1", port), timeout=10)
            c.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n")
            c.sendall(frame(4, 0, 0, b""))  # SETTINGS
            heads = (hp(b":method", b"POST") + hp(b":path", b"/t")
                     + hp(b":scheme", b"http") + hp(b":authority", b"t")
                     + hp(b"user-agent", b"trail/1.0"))
            c.sendall(frame(1, 0x4, 1, heads))       # HEADERS, no ES
            c.sendall(frame(0, 0, 1, b"BODYBYTES"))  # DATA, no ES
            trailers = hp(b"x-checksum", b"abc123")
            c.sendall(frame(1, 0x5, 1, trailers))    # trailers: ES+EH
            assert done.wait(20), "upstream never saw the finished body"
            assert got["body"] == b"BODYBYTES", got
            # response HEADERS for stream 1 must come back
            c.settimeout(10)
            buf = b""
            saw_resp = False
            deadline = time.time() + 10
            while time.time() < deadline and not saw_resp:
                try:
                    ch = c.recv(65536)
                except socket.timeout:
                    break
                if not ch:
                    break
                buf += ch
                while len(buf) >= 9:
                    ln = int.from_bytes(buf[:3], "big")
                    if len(buf) < 9 + ln:
                        break
                    ftype = buf[3]
                    fsid = int.from_bytes(buf[5:9], "big") & 0x7FFFFFFF
                    if ftype == 1 and fsid == 1:
                        saw_resp = True
                    buf = buf[9 + ln:]
            assert saw_resp, "no response HEADERS on stream 1"
            c.close()
        finally:
            drain.kill()
            h.kill()
            ring.close()
            pong.shutdown()


class TestFullStackCombinedConfig:
    """One CLI-driven config exercising every native-plane capability
    at once: an h2:// upstream service with a route, a static service
    with a route, a catch-all h1 proxy, a WAF rule, and a native TCP
    listener — the closest thing to a production deployment the test
    suite drives."""

    def test_cli_combined_deployment(self, tmp_path, loop_runner):
        import textwrap
        import urllib.request

        from pingoo_tpu.config import load_and_validate
        from pingoo_tpu.host.native_plane import NativePlane

        # h2c upstream: a second native httpd fronting a tagged pong
        pong = _tagged_upstream("svc-pong")
        h2_port = _free_port()
        ring_b = Ring(str(tmp_path / "rb"), capacity=256, create=True)
        drain_b = subprocess.Popen(
            [os.path.join(native_ring.NATIVE_DIR, "drain"),
             str(tmp_path / "rb")], stdout=subprocess.PIPE)
        assert b"draining" in drain_b.stdout.readline()
        h2up = subprocess.Popen(
            [HTTPD, str(h2_port), str(tmp_path / "rb"), "127.0.0.1",
             str(pong.server_address[1])], stdout=subprocess.PIPE)
        assert b"listening" in h2up.stdout.readline()

        echo = _tcp_echo_upstream(b"tcp:")

        site = tmp_path / "site"
        (site / "static").mkdir(parents=True)
        # the `site` route matches /static/*; paths resolve under the
        # root, so the file lives at <root>/static/page.html
        (site / "static" / "page.html").write_text("<h1>combined</h1>")
        app = _tagged_upstream("svc-app")
        port, tcp_port = _free_port(), _free_port()
        cfg = tmp_path / "pingoo.yml"
        cfg.write_text(textwrap.dedent(f"""
        listeners:
          main:
            address: "http://127.0.0.1:{port}"
            services: [api, site, app]
          db:
            address: "tcp://127.0.0.1:{tcp_port}"
            services: [dbsvc]
        services:
          api:
            http_proxy: ["h2://127.0.0.1:{h2_port}"]
            route: http_request.path.starts_with("/api")
          site:
            static: {{root: "{site}"}}
            route: http_request.path.starts_with("/static")
          app:
            http_proxy: ["http://127.0.0.1:{app.server_address[1]}"]
          dbsvc:
            tcp_proxy: ["tcp://127.0.0.1:{echo.getsockname()[1]}"]
        rules:
          block-env:
            expression: http_request.path.starts_with("/.env")
            actions: [{{action: block}}]
        """))
        config = load_and_validate(str(cfg))
        plane = NativePlane(
            config, state_dir=str(tmp_path / "state"), use_device=False,
            enable_docker=False,
            geoip_paths=(str(tmp_path / "missing.mmdb"),),
            captcha_jwks_path=str(tmp_path / "jwks.json"),
            tls_dir=str(tmp_path / "tls"))
        loop_runner.run(plane.start(), timeout=180)
        try:
            def get(path):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}{path}",
                    headers={"user-agent": "full/1.0"})
                try:
                    with urllib.request.urlopen(req, timeout=30) as r:
                        return r.status, r.read()
                except urllib.error.HTTPError as e:
                    return e.code, e.read()

            # warm routing (fail-open to service 0 during first compile)
            deadline = time.time() + 60
            while time.time() < deadline:
                st, body = get("/")
                if st == 200 and b"svc-app:/" in body:
                    break
                time.sleep(0.5)
            assert b"svc-app:/" in body, (st, body)
            # h2:// upstream, natively framed
            deadline = time.time() + 30
            while time.time() < deadline:
                st, body = get("/api/x")
                if body == b"svc-pong:/api/x":
                    break
                time.sleep(0.5)
            assert st == 200 and body == b"svc-pong:/api/x", (st, body)
            # native static (with .html prettify) via the routed service
            st, body = get("/static/page")
            assert st == 200 and b"<h1>combined</h1>" in body, (st, body)
            # WAF applies before everything
            st, _ = get("/.env")
            assert st == 403
            # native tcp
            c = socket.create_connection(("127.0.0.1", tcp_port),
                                         timeout=10)
            c.settimeout(10)
            c.sendall(b"ping")
            assert c.recv(100) == b"tcp:ping"
            c.close()
        finally:
            loop_runner.run(plane.stop(), timeout=60)
            drain_b.kill()
            h2up.kill()
            ring_b.close()
            echo.close()
            pong.shutdown()
            app.shutdown()
