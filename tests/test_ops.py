"""Device-op tests: JAX NFA scan vs numpy reference; match ops and CIDR
ops vs Python oracles."""

import ipaddress
import random
import re

import numpy as np
import pytest

from pingoo_tpu.compiler.nfa import build_bank, scan_numpy
from pingoo_tpu.compiler.repat import compile_regex
from pingoo_tpu.expr.values import Ip
from pingoo_tpu.ops.cidr import (
    build_cidr_table,
    build_int_set,
    build_v4_buckets,
    cidr_contains,
    cidr_match_one,
    encode_ip_batch,
    int_set_contains,
    ip_to_words,
    v4_buckets_contains,
)
from pingoo_tpu.ops.match_ops import (
    build_pattern_table,
    build_suffix_table,
    eq_match,
    prefix_match,
    reverse_bytes,
    suffix_match,
)
from pingoo_tpu.ops.nfa_scan import bank_to_tables, nfa_scan


def to_matrix(inputs, L=None):
    L = L or max(1, max(len(d) for d in inputs))
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lens = np.zeros(len(inputs), dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
        lens[i] = min(len(d), L)
    return mat, lens


class TestNfaScanJax:
    def test_matches_numpy_reference(self):
        patterns = []
        sources = [r"abc", r"^/api", r"\.php$", r"(?i)select", r"a.c",
                   r"x{2,3}y", r"[0-9]+", r"^GET$", r"a*b", r"q?q?z$"]
        for src in sources:
            patterns.extend(compile_regex(src))
        bank = build_bank(patterns)
        tables = bank_to_tables(bank)

        rng = random.Random(99)
        alphabet = b"abcqxyGETselct0123456789/.php\nSELECT "
        inputs = [b"", b"\n", b"abc", b"/api/x.php", b"GET", b"SELECT 1",
                  b"xxy", b"xxxy", b"qz", b"qqz\n"]
        for _ in range(80):
            k = rng.randint(0, 30)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        mat, lens = to_matrix(inputs)
        want = scan_numpy(bank, mat, lens)
        got = np.asarray(nfa_scan(tables, mat, lens))
        assert got.shape == want.shape
        np.testing.assert_array_equal(got, want)

    def test_agrees_with_re_end_to_end(self):
        sources = [r"(?i)union\s+select", r"etc/passwd", r"^/admin", r"\.env$"]
        patterns, spans = [], []
        for src in sources:
            alts = compile_regex(src)
            spans.append((len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        tables = bank_to_tables(build_bank(patterns))
        inputs = [b"/admin/login", b"UNION  SELECT", b"/app/.env", b"clean",
                  b"/etc/passwd", b"union select", b"x.env.bak"]
        mat, lens = to_matrix(inputs)
        got = np.asarray(nfa_scan(tables, mat, lens))
        for (lo, hi), src in zip(spans, sources):
            gold = re.compile(src.encode())
            for i, d in enumerate(inputs):
                assert got[i, lo:hi].any() == (gold.search(d) is not None), (
                    src, d)


class TestMatchOps:
    def test_prefix_eq_suffix(self):
        inputs = [b"/index.html", b"/.env", b"/.env.local", b"/api/v1",
                  b"", b"/INDEX.HTML"]
        mat, lens = to_matrix(inputs)
        pats = [(b"/.env", False), (b"/index", False), (b"/index", True),
                (b"", False)]
        table = build_pattern_table(pats)
        got = np.asarray(prefix_match(mat, lens, table))
        for i, d in enumerate(inputs):
            for j, (p, ci) in enumerate(pats):
                want = (d.lower() if ci else d).startswith(p.lower() if ci else p)
                assert got[i, j] == want, (d, p, ci)

        eq_table = build_pattern_table([(b"/.env", False), (b"", False)])
        got = np.asarray(eq_match(mat, lens, eq_table))
        for i, d in enumerate(inputs):
            assert got[i, 0] == (d == b"/.env")
            assert got[i, 1] == (d == b"")

        spats = [(b".html", False), (b".env", False), (b".HTML", True)]
        stable = build_suffix_table(spats)
        rev = reverse_bytes(mat, lens)
        got = np.asarray(suffix_match(rev, lens, stable))
        for i, d in enumerate(inputs):
            for j, (p, ci) in enumerate(spats):
                want = (d.lower() if ci else d).endswith(p.lower() if ci else p)
                assert got[i, j] == want, (d, p, ci)

    def test_pattern_longer_than_field(self):
        mat, lens = to_matrix([b"abc"], L=3)
        table = build_pattern_table([(b"abcdef", False)])
        assert not np.asarray(prefix_match(mat, lens, table))[0, 0]
        assert not np.asarray(eq_match(mat, lens, table))[0, 0]


def rand_ip(rng):
    return ipaddress.ip_address(rng.getrandbits(32))


class TestCidrOps:
    def test_masked_compare_table(self):
        entries = [Ip("10.0.0.0/8"), Ip("192.0.2.1"), Ip("2001:db8::/32"),
                   Ip("0.0.0.0/0") if False else Ip("172.16.0.0/12")]
        table = build_cidr_table(entries)
        probes = [Ip("10.1.2.3"), Ip("192.0.2.1"), Ip("192.0.2.2"),
                  Ip("2001:db8::5"), Ip("8.8.8.8"), Ip("172.31.255.255"),
                  Ip("172.32.0.0")]
        ips = encode_ip_batch(probes)
        got = np.asarray(cidr_contains(table, ips))
        for i, probe in enumerate(probes):
            want = any(e.contains(probe) for e in entries)
            assert got[i] == want, probe

    def test_single_cidr_and_literal_ip(self):
        probes = [Ip("203.0.113.7"), Ip("203.0.113.8"), Ip("2001:db8::1")]
        ips = encode_ip_batch(probes)
        words, prefix = ip_to_words(Ip("203.0.113.7"))
        got = np.asarray(cidr_match_one(words, prefix, ips))
        assert got.tolist() == [True, False, False]
        words, prefix = ip_to_words(Ip("203.0.113.0/24"))
        got = np.asarray(cidr_match_one(words, prefix, ips))
        assert got.tolist() == [True, True, False]

    def test_v4_buckets_large_list(self):
        rng = random.Random(5)
        entries = [Ip(str(rand_ip(rng))) for _ in range(500)]
        entries += [Ip(f"{rng.randrange(256)}.{rng.randrange(256)}.0.0/16")
                    for _ in range(50)]
        entries += [Ip("10.0.0.0/8"), Ip("2001:db8::/32"), Ip("0.0.0.0/5")]
        buckets = build_v4_buckets(entries)
        probes = [Ip(str(rand_ip(rng))) for _ in range(300)]
        probes += [entries[0], entries[3], Ip("10.9.9.9"), Ip("2001:db8::9"),
                   Ip("3.0.0.1")]
        ips = encode_ip_batch(probes)
        got = np.asarray(v4_buckets_contains(buckets, ips))
        for i, probe in enumerate(probes):
            want = any(e.contains(probe) for e in entries)
            assert got[i] == want, probe

    def test_int_set(self):
        table = build_int_set([64500, 64501, 15169, -5])
        import jax.numpy as jnp

        vals = jnp.asarray(np.array([64500, 64502, 15169, -5, 0], dtype=np.int64))
        got = np.asarray(int_set_contains(table, vals))
        assert got.tolist() == [True, False, True, True, False]

    def test_empty_tables(self):
        table = build_cidr_table([])
        ips = encode_ip_batch([Ip("1.2.3.4")])
        assert not np.asarray(cidr_contains(table, ips))[0]
        buckets = build_v4_buckets([])
        assert not np.asarray(v4_buckets_contains(buckets, ips))[0]
        iset = build_int_set([])
        import jax.numpy as jnp

        assert not np.asarray(
            int_set_contains(iset, jnp.asarray(np.array([0], dtype=np.int64)))
        )[0]


class TestMultiWordJax:
    def test_multiword_matches_numpy_and_re(self):
        """The jitted scan agrees with scan_numpy and re on multi-word
        banks (cross-word carry + escape passes + pair extraction)."""
        sources = [r"abc", "x" * 40, r"<svg[^>]{0,40}onload",
                   "(?i)" + "union" * 8, "b" * 45 + "$",
                   r"\b" + "w" * 40 + r"\b", "e{0,60}f", r"\.php$"]
        patterns, spans = [], []
        for src in sources:
            alts = compile_regex(src)
            spans.append((len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        bank = build_bank(patterns)
        assert bank.has_carry and bank.prop_passes >= 2
        tables = bank_to_tables(bank)
        # \b patterns emit multiple accept pairs -> the matmul (non-
        # identity) extraction path must be the one under test here.
        assert not tables.identity_accept

        rng = random.Random(424242)
        inputs = [b"x" * 40, b"<svg " + b"a" * 39 + b"onload",
                  b"UNION" * 8, b"b" * 45, b"b" * 45 + b"\n",
                  b" " + b"w" * 40 + b".", b"e" * 30 + b"f", b"x.php",
                  b"x" * 39, b"w" * 41, b""]
        alphabet = b"xwabeunion<svg>.php$ 0123456789"
        for _ in range(60):
            k = rng.randint(0, 90)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        mat, lens = to_matrix(inputs)
        want = scan_numpy(bank, mat, lens)
        got = np.asarray(nfa_scan(tables, mat, lens))
        np.testing.assert_array_equal(got, want)
        for (lo, hi), src in zip(spans, sources):
            gold = re.compile(src.encode())
            for i, d in enumerate(inputs):
                assert got[i, lo:hi].any() == (gold.search(d) is not None), (
                    src, d)
