"""Device-op tests: JAX NFA scan vs numpy reference; match ops and CIDR
ops vs Python oracles."""

import ipaddress
import random
import re

import numpy as np
import pytest

from pingoo_tpu.compiler.nfa import build_bank, scan_numpy
from pingoo_tpu.compiler.repat import compile_regex
from pingoo_tpu.expr.values import Ip
from pingoo_tpu.ops.cidr import (
    build_cidr_table,
    build_int_set,
    build_v4_buckets,
    cidr_contains,
    cidr_match_one,
    encode_ip_batch,
    int_set_contains,
    ip_to_words,
    v4_buckets_contains,
)
from pingoo_tpu.ops.match_ops import (
    build_pattern_table,
    build_suffix_table,
    eq_match,
    prefix_match,
    suffix_match,
)
from pingoo_tpu.ops.nfa_scan import bank_to_tables, nfa_scan


def to_matrix(inputs, L=None):
    L = L or max(1, max(len(d) for d in inputs))
    mat = np.zeros((len(inputs), L), dtype=np.uint8)
    lens = np.zeros(len(inputs), dtype=np.int32)
    for i, d in enumerate(inputs):
        mat[i, : len(d)] = np.frombuffer(d[:L], dtype=np.uint8)
        lens[i] = min(len(d), L)
    return mat, lens


class TestNfaScanJax:
    def test_matches_numpy_reference(self):
        patterns = []
        sources = [r"abc", r"^/api", r"\.php$", r"(?i)select", r"a.c",
                   r"x{2,3}y", r"[0-9]+", r"^GET$", r"a*b", r"q?q?z$"]
        for src in sources:
            patterns.extend(compile_regex(src))
        bank = build_bank(patterns)
        tables = bank_to_tables(bank)

        rng = random.Random(99)
        alphabet = b"abcqxyGETselct0123456789/.php\nSELECT "
        inputs = [b"", b"\n", b"abc", b"/api/x.php", b"GET", b"SELECT 1",
                  b"xxy", b"xxxy", b"qz", b"qqz\n"]
        for _ in range(80):
            k = rng.randint(0, 30)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        mat, lens = to_matrix(inputs)
        want = scan_numpy(bank, mat, lens)
        got = np.asarray(nfa_scan(tables, mat, lens))
        assert got.shape == want.shape
        np.testing.assert_array_equal(got, want)

    def test_agrees_with_re_end_to_end(self):
        sources = [r"(?i)union\s+select", r"etc/passwd", r"^/admin", r"\.env$"]
        patterns, spans = [], []
        for src in sources:
            alts = compile_regex(src)
            spans.append((len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        tables = bank_to_tables(build_bank(patterns))
        inputs = [b"/admin/login", b"UNION  SELECT", b"/app/.env", b"clean",
                  b"/etc/passwd", b"union select", b"x.env.bak"]
        mat, lens = to_matrix(inputs)
        got = np.asarray(nfa_scan(tables, mat, lens))
        for (lo, hi), src in zip(spans, sources):
            gold = re.compile(src.encode())
            for i, d in enumerate(inputs):
                assert got[i, lo:hi].any() == (gold.search(d) is not None), (
                    src, d)


class TestMatchOps:
    def test_prefix_eq_suffix(self):
        inputs = [b"/index.html", b"/.env", b"/.env.local", b"/api/v1",
                  b"", b"/INDEX.HTML"]
        mat, lens = to_matrix(inputs)
        pats = [(b"/.env", False), (b"/index", False), (b"/index", True),
                (b"", False)]
        table = build_pattern_table(pats)
        got = np.asarray(prefix_match(mat, lens, table))
        for i, d in enumerate(inputs):
            for j, (p, ci) in enumerate(pats):
                want = (d.lower() if ci else d).startswith(p.lower() if ci else p)
                assert got[i, j] == want, (d, p, ci)

        eq_table = build_pattern_table([(b"/.env", False), (b"", False)])
        got = np.asarray(eq_match(mat, lens, eq_table))
        for i, d in enumerate(inputs):
            assert got[i, 0] == (d == b"/.env")
            assert got[i, 1] == (d == b"")

        spats = [(b".html", False), (b".env", False), (b".HTML", True),
                 (b"", False)]
        stable = build_suffix_table(spats)
        got = np.asarray(suffix_match(mat, lens, stable))
        for i, d in enumerate(inputs):
            for j, (p, ci) in enumerate(spats):
                want = (d.lower() if ci else d).endswith(p.lower() if ci else p)
                assert got[i, j] == want, (d, p, ci)

    def test_suffix_longer_than_row(self):
        mat, lens = to_matrix([b"ab", b"xyzab"], L=8)
        stable = build_suffix_table([(b"zab", False), (b"ab", False)])
        got = np.asarray(suffix_match(mat, lens, stable))
        assert got.tolist() == [[False, True], [True, True]]

    def test_pattern_longer_than_field(self):
        mat, lens = to_matrix([b"abc"], L=3)
        table = build_pattern_table([(b"abcdef", False)])
        assert not np.asarray(prefix_match(mat, lens, table))[0, 0]
        assert not np.asarray(eq_match(mat, lens, table))[0, 0]


def rand_ip(rng):
    return ipaddress.ip_address(rng.getrandbits(32))


class TestCidrOps:
    def test_masked_compare_table(self):
        entries = [Ip("10.0.0.0/8"), Ip("192.0.2.1"), Ip("2001:db8::/32"),
                   Ip("0.0.0.0/0") if False else Ip("172.16.0.0/12")]
        table = build_cidr_table(entries)
        probes = [Ip("10.1.2.3"), Ip("192.0.2.1"), Ip("192.0.2.2"),
                  Ip("2001:db8::5"), Ip("8.8.8.8"), Ip("172.31.255.255"),
                  Ip("172.32.0.0")]
        ips = encode_ip_batch(probes)
        got = np.asarray(cidr_contains(table, ips))
        for i, probe in enumerate(probes):
            want = any(e.contains(probe) for e in entries)
            assert got[i] == want, probe

    def test_single_cidr_and_literal_ip(self):
        probes = [Ip("203.0.113.7"), Ip("203.0.113.8"), Ip("2001:db8::1")]
        ips = encode_ip_batch(probes)
        words, prefix = ip_to_words(Ip("203.0.113.7"))
        got = np.asarray(cidr_match_one(words, prefix, ips))
        assert got.tolist() == [True, False, False]
        words, prefix = ip_to_words(Ip("203.0.113.0/24"))
        got = np.asarray(cidr_match_one(words, prefix, ips))
        assert got.tolist() == [True, True, False]

    def test_v4_buckets_large_list(self):
        rng = random.Random(5)
        entries = [Ip(str(rand_ip(rng))) for _ in range(500)]
        entries += [Ip(f"{rng.randrange(256)}.{rng.randrange(256)}.0.0/16")
                    for _ in range(50)]
        entries += [Ip("10.0.0.0/8"), Ip("2001:db8::/32"), Ip("0.0.0.0/5")]
        buckets = build_v4_buckets(entries)
        probes = [Ip(str(rand_ip(rng))) for _ in range(300)]
        probes += [entries[0], entries[3], Ip("10.9.9.9"), Ip("2001:db8::9"),
                   Ip("3.0.0.1")]
        ips = encode_ip_batch(probes)
        got = np.asarray(v4_buckets_contains(buckets, ips))
        for i, probe in enumerate(probes):
            want = any(e.contains(probe) for e in entries)
            assert got[i] == want, probe

    def test_int_set(self):
        table = build_int_set([64500, 64501, 15169, -5])
        import jax.numpy as jnp

        vals = jnp.asarray(np.array([64500, 64502, 15169, -5, 0], dtype=np.int64))
        got = np.asarray(int_set_contains(table, vals))
        assert got.tolist() == [True, False, True, True, False]

    def test_empty_tables(self):
        table = build_cidr_table([])
        ips = encode_ip_batch([Ip("1.2.3.4")])
        assert not np.asarray(cidr_contains(table, ips))[0]
        buckets = build_v4_buckets([])
        assert not np.asarray(v4_buckets_contains(buckets, ips))[0]
        iset = build_int_set([])
        import jax.numpy as jnp

        assert not np.asarray(
            int_set_contains(iset, jnp.asarray(np.array([0], dtype=np.int64)))
        )[0]


class TestMultiWordJax:
    def test_multiword_matches_numpy_and_re(self):
        """The jitted scan agrees with scan_numpy and re on multi-word
        banks (cross-word carry + escape passes + pair extraction)."""
        sources = [r"abc", "x" * 40, r"<svg[^>]{0,40}onload",
                   "(?i)" + "union" * 8, "b" * 45 + "$",
                   r"\b" + "w" * 40 + r"\b", "e{0,60}f", r"\.php$"]
        patterns, spans = [], []
        for src in sources:
            alts = compile_regex(src)
            spans.append((len(patterns), len(patterns) + len(alts)))
            patterns.extend(alts)
        bank = build_bank(patterns)
        assert bank.has_carry and bank.prop_passes >= 2
        tables = bank_to_tables(bank)
        # \b patterns emit multiple accept pairs -> the matmul (non-
        # identity) extraction path must be the one under test here.
        assert not tables.identity_accept

        rng = random.Random(424242)
        inputs = [b"x" * 40, b"<svg " + b"a" * 39 + b"onload",
                  b"UNION" * 8, b"b" * 45, b"b" * 45 + b"\n",
                  b" " + b"w" * 40 + b".", b"e" * 30 + b"f", b"x.php",
                  b"x" * 39, b"w" * 41, b""]
        alphabet = b"xwabeunion<svg>.php$ 0123456789"
        for _ in range(60):
            k = rng.randint(0, 90)
            inputs.append(bytes(rng.choice(alphabet) for _ in range(k)))
        mat, lens = to_matrix(inputs)
        want = scan_numpy(bank, mat, lens)
        got = np.asarray(nfa_scan(tables, mat, lens))
        np.testing.assert_array_equal(got, want)
        for (lo, hi), src in zip(spans, sources):
            gold = re.compile(src.encode())
            for i, d in enumerate(inputs):
                assert got[i, lo:hi].any() == (gold.search(d) is not None), (
                    src, d)


class TestV4BucketIndex:
    def test_clustered_keys_slot_index(self):
        """Keys crammed into few top-16 slots stress the slot-span binary
        search (span >> 1); parity vs the plain searchsorted path."""
        import jax.numpy as jnp
        from pingoo_tpu.ops.cidr import index_v4_buckets, SLOT_BITS

        rng = random.Random(9)
        # 5000 /32 keys all inside 10.0.0.0/18 -> a handful of slots.
        base = 10 << 24
        addrs = sorted({base + rng.randrange(1 << 18) for _ in range(5000)})
        keys = np.array([addrs], dtype=np.uint32)
        sizes = np.array([len(addrs)], dtype=np.int32)
        prefixes = np.array([32], dtype=np.int32)
        indexed = index_v4_buckets(keys, prefixes, sizes, build_cidr_table([]))
        plain = indexed._replace(starts=None, span_pad=None)
        probes = [Ip(str(ipaddress.ip_address(base + rng.randrange(1 << 18))))
                  for _ in range(200)]
        probes += [Ip(str(ipaddress.ip_address(a))) for a in addrs[:50]]
        ips = encode_ip_batch(probes)
        got = np.asarray(v4_buckets_contains(indexed, ips))
        want = np.asarray(v4_buckets_contains(plain, ips))
        assert (got == want).all()
        member = set(addrs)
        for i, p in enumerate(probes):
            assert got[i] == (int(p.addr) in member)

    def test_low_prefix_buckets_indexed(self):
        """Buckets with prefix < SLOT_BITS (keys shorter than the slot
        id) still index correctly: hi == key."""
        entries = [Ip("10.0.0.0/8"), Ip("11.0.0.0/8"), Ip("192.168.0.0/16")]
        buckets = build_v4_buckets(entries)
        assert buckets.starts is not None
        probes = [Ip("10.200.1.1"), Ip("11.0.0.1"), Ip("12.0.0.1"),
                  Ip("192.168.3.4"), Ip("192.169.0.1")]
        ips = encode_ip_batch(probes)
        got = np.asarray(v4_buckets_contains(buckets, ips))
        want = [any(e.contains(p) for e in entries) for p in probes]
        assert got.tolist() == want


class TestWindowMatch:
    def _hits(self, patterns, inputs, L=None):
        from pingoo_tpu.ops.window_match import build_window_table, window_hits

        table = build_window_table(patterns)
        mat, lens = to_matrix(inputs, L=L)
        return np.asarray(window_hits(table, mat, lens))

    def test_literal_fold_any_vs_re(self):
        from pingoo_tpu.compiler.repat import compile_regex, to_window

        sources = [r"sqlmap", r"(?i)nikto", r"(?i)python-requests/1\.",
                   r"(?i)<script", r"\$\{jndi:", r"(?i)union"]
        pats, keep = [], []
        for src in sources:
            alts = compile_regex(src)
            wins = [to_window(lp) for lp in alts]
            assert all(w is not None for w in wins), src
            pats.extend(wins)
            keep.append(src)
        inputs = [b"", b"sqlmap/1.8", b"SQLMAP", b"Nikto/2.5.0",
                  b"python-requests/1.9", b"python-requests/2.0",
                  b"x<SCRipt>alert(1)", b"a${jndi:ldap://x}", b"UNION SELECT",
                  b"clean mozilla agent", b"sqlma", b"qlmap"]
        got = self._hits(pats, inputs)
        for j, src in enumerate(keep):
            gold = re.compile(src.encode())
            for i, d in enumerate(inputs):
                assert got[i, j] == (gold.search(d) is not None), (src, d)

    def test_window_respects_length_mask(self):
        """Bytes past lengths[b] are dead even if present in the buffer."""
        from pingoo_tpu.compiler.repat import compile_regex, to_window

        pats = [to_window(compile_regex("abc")[0])]
        mat, lens = to_matrix([b"xxabc"], L=8)
        lens[0] = 3  # only b"xxa" is live
        from pingoo_tpu.ops.window_match import build_window_table, window_hits
        got = np.asarray(window_hits(build_window_table(pats), mat, lens))
        assert not got[0, 0]
        lens[0] = 5
        got = np.asarray(window_hits(build_window_table(pats), mat, lens))
        assert got[0, 0]

    def test_edge_optional_stripping(self):
        from pingoo_tpu.compiler.repat import compile_regex, to_window

        # Trailing star/opt and edge plus are strippable; mid-pattern
        # optionals and non-fold classes are not.
        assert to_window(compile_regex(r"(?i)tok3n[0-9a-f]*")[0]) is not None
        assert to_window(compile_regex(r"ab?")[0]) is not None
        assert to_window(compile_regex(r"ab+")[0]) is not None
        assert to_window(compile_regex(r"a[0-9]c")[0]) is None
        assert to_window(compile_regex(r"a.c")[0]) is None  # . excludes \n
        assert to_window(compile_regex(r"^abc")[0]) is None
        assert to_window(compile_regex(r"abc$")[0]) is None
        assert to_window(compile_regex(r"\babc")[0]) is None
        assert to_window(compile_regex(r"ab?c")[0]) is None

    def test_edge_plus_and_star_vs_re(self):
        from pingoo_tpu.compiler.repat import compile_regex, to_window

        for src in (r"ab+", r"(?i)tok3n[0-9a-f]*", r"x*yz"):
            alts = compile_regex(src)
            wins = [to_window(lp) for lp in alts]
            assert all(w is not None for w in wins), src
            gold = re.compile(src.encode())
            inputs = [b"", b"a", b"ab", b"abb", b"TOK3Nff", b"tok3n",
                      b"yz", b"xxyz", b"xy", b"zzz"]
            got = self._hits(wins, inputs)
            for i, d in enumerate(inputs):
                assert got[i].any() == (gold.search(d) is not None), (src, d)

    def test_plan_routes_literalish_leaves_to_window(self):
        from pingoo_tpu.compiler import compile_ruleset
        from pingoo_tpu.config.schema import Action, RuleConfig
        from pingoo_tpu.expr import compile_expression

        rules = [
            RuleConfig(name="ua", actions=(Action.BLOCK,), expression=
                compile_expression('http_request.user_agent.matches("(?i)sqlmap")')),
            RuleConfig(name="kw", actions=(Action.BLOCK,), expression=
                compile_expression('http_request.url.contains("<?php")')),
            RuleConfig(name="rx", actions=(Action.BLOCK,), expression=
                compile_expression(r'http_request.url.matches("sleep\\(\\d+\\)")')),
        ]
        plan = compile_ruleset(rules, {})
        kinds = {b.kind for b in plan.bindings.values()}
        assert "window" in kinds and "nfa" in kinds
        win_fields = {b.field for b in plan.bindings.values()
                      if b.kind == "window"}
        assert win_fields == {"user_agent", "url"}
