"""Unified telemetry layer (pingoo_tpu/obs): registry semantics,
Prometheus exposition lint, the cross-plane metrics-schema parity
contract, trace ids, and access-log sampling. No accelerator needed —
the one jax-touching test (per-stage service histograms) runs via the
CPU-pinned VerdictService like the rest of tier 1."""

import json
import logging

import pytest

from pingoo_tpu.obs import schema
from pingoo_tpu.obs.registry import (
    LATENCY_BUCKETS_MS,
    WAIT_BUCKETS_MS,
    MetricRegistry,
    lint_prometheus_text,
)
from pingoo_tpu.obs.trace import AccessLogSampler, new_trace_id


class TestRegistry:
    def test_counter_gauge_roundtrip(self):
        reg = MetricRegistry()
        c = reg.counter("t_total", "help")
        c.inc()
        c.inc(4)
        assert c.value == 5
        # get-or-create: same (name, labels) -> same instrument
        assert reg.counter("t_total") is c
        g = reg.gauge("t_depth", "help", labels={"plane": "x"})
        g.set(7)
        g.dec()
        assert g.value == 6
        assert reg.gauge("t_depth", labels={"plane": "y"}) is not g

    def test_histogram_buckets_and_percentiles(self):
        reg = MetricRegistry()
        h = reg.histogram("t_ms", "help", buckets=WAIT_BUCKETS_MS)
        for v in (0.4, 1.5, 1.5, 7, 60, 5000):
            h.observe(v)
        assert h.count == 6
        snap = h.snapshot()
        assert snap["buckets"]["1"] == 1
        assert snap["buckets"]["2"] == 3
        assert snap["buckets"]["+Inf"] == 6
        assert h.percentile(0.5) == 2.0  # bucket-upper-bound estimate
        # +inf observations report the largest finite bound
        assert h.percentile(1.0) == 1000.0

    def test_histogram_external_bucket_mirror(self):
        reg = MetricRegistry()
        h = reg.histogram("t_wait_ms", "", buckets=WAIT_BUCKETS_MS)
        h.set_bucket_counts([2, 1, 0, 0, 0, 0, 0, 1], total_sum=2000.0)
        assert h.count == 4
        assert h.sum == 2000.0
        assert h.percentile(0.5) == 1.0
        with pytest.raises(ValueError):
            h.set_bucket_counts([1, 2, 3])  # wrong arity

    def test_prometheus_text_lints_clean(self):
        reg = MetricRegistry()
        reg.counter("pingoo_requests_total", "requests",
                    labels={"plane": "python", "listener": "l0"}).inc(3)
        reg.gauge("pingoo_ring_depth", "depth",
                  labels={"plane": "native"}).set(2)
        h = reg.histogram("pingoo_verdict_wait_ms", "wait",
                          buckets=WAIT_BUCKETS_MS,
                          labels={"plane": "python"})
        for v in (0.5, 3, 3, 42, 1500):
            h.observe(v)
        text = reg.prometheus_text()
        assert lint_prometheus_text(text) == []
        assert ('pingoo_requests_total{listener="l0",plane="python"} 3'
                in text)
        assert ('pingoo_verdict_wait_ms_bucket{le="+Inf",plane="python"} 5'
                in text)
        assert "# TYPE pingoo_verdict_wait_ms histogram" in text

    def test_lint_catches_broken_exposition(self):
        bad = ("# TYPE x_total counter\n"
               "x_total{le=} 3\n")
        assert lint_prometheus_text(bad)
        no_type = "lonely_metric 1\n"
        assert any("without TYPE" in p
                   for p in lint_prometheus_text(no_type))
        non_cumulative = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 5\n'
            'h_bucket{le="2"} 3\n'
            'h_bucket{le="+Inf"} 5\n'
            "h_sum 1\nh_count 5\n")
        assert any("cumulative" in p
                   for p in lint_prometheus_text(non_cumulative))
        missing_inf = (
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 1\n'
            "h_sum 1\nh_count 1\n")
        assert any("+Inf" in p for p in lint_prometheus_text(missing_inf))

    def test_collectors_fold_external_sources(self):
        reg = MetricRegistry()

        def collect():
            reg.counter("ext_total", "ext").set_total(42)

        reg.register_collector(collect)
        assert "ext_total 42" in reg.prometheus_text()
        snap = reg.json_snapshot()
        assert snap["ext_total"] == 42
        reg.unregister_collector(collect)

    def test_broken_collector_never_breaks_scrape(self):
        reg = MetricRegistry()
        reg.counter("ok_total", "x").inc()

        def broken():
            raise RuntimeError("ring unmapped")

        reg.register_collector(broken)
        assert "ok_total 1" in reg.prometheus_text()

    def test_stage_snapshot_keys_by_plane(self):
        reg = MetricRegistry()
        reg.histogram("pingoo_verdict_stage_ms", "",
                      buckets=LATENCY_BUCKETS_MS,
                      labels={"plane": "python",
                              "stage": "encode"}).observe(0.3)
        reg.histogram("pingoo_verdict_stage_ms", "",
                      buckets=LATENCY_BUCKETS_MS,
                      labels={"plane": "sidecar",
                              "stage": "encode"}).observe(0.4)
        snap = reg.stage_snapshot()
        assert set(snap) == {"python:encode", "sidecar:encode"}
        assert snap["python:encode"]["count"] == 1


class TestSchemaParity:
    """The cross-surface contract (ISSUE 2 satellite): every plane uses
    the same metric names for shared concepts. The native plane's
    exposition is C++ string literals, so the source IS the schema."""

    def _native_source(self):
        import os

        path = os.path.join(os.path.dirname(__file__), "..",
                            "pingoo_tpu", "native", "httpd.cc")
        with open(path) as f:
            return f.read()

    def test_native_exposes_inventory(self):
        src = self._native_source()
        for name in (set(schema.SHARED_METRICS) | set(schema.RING_METRICS)
                     | set(schema.NATIVE_METRICS)):
            assert name in src, f"native plane missing {name}"
        assert schema.SHARED_WAIT_HISTOGRAM + "_bucket" in src
        for key in schema.NATIVE_JSON_KEYS:
            assert f'"{key}"' in src

    def test_python_listener_exposes_shared_names(self):
        import pingoo_tpu.host.httpd as httpd_mod
        import inspect

        src = inspect.getsource(httpd_mod)
        for name in schema.SHARED_METRICS:
            assert name in src, f"python listener missing {name}"

    def test_sidecar_exports_ring_names(self):
        import pingoo_tpu.native_ring as nr
        import inspect

        src = inspect.getsource(nr)
        for name in schema.RING_METRICS:
            assert name in src, f"sidecar missing {name}"

    def test_wait_buckets_match_everywhere(self):
        # Python registry bounds == documented shared bounds == the
        # native record_wait bounds == the ring telemetry bounds.
        from pingoo_tpu.native_ring import WAIT_BUCKET_BOUNDS_MS

        assert tuple(WAIT_BUCKET_BOUNDS_MS) == schema.SHARED_WAIT_BUCKETS_MS
        assert tuple(int(b) for b in WAIT_BUCKETS_MS) == \
            schema.SHARED_WAIT_BUCKETS_MS
        src = self._native_source()
        assert "{1, 2, 5, 10, 50, 100, 1000}" in src
        # audit tool agrees end-to-end
        import subprocess
        import sys
        import os

        repo = os.path.join(os.path.dirname(__file__), "..")
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "tools",
                                          "check_metrics_schema.py")],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr


class TestServiceStages:
    def test_service_stats_snapshot_backcompat_keys(self):
        from pingoo_tpu.engine.service import ServiceStats

        stats = ServiceStats()
        snap = stats.snapshot()
        # the pre-registry schema keys survive (back-compat contract)
        for key in ("batches", "requests", "device_errors", "score_errors",
                    "host_fallback_batches", "mean_occupancy",
                    "verdict_p50_ms", "verdict_p99_ms"):
            assert key in snap, key
        assert set(snap["stages"]) == set(schema.VERDICT_STAGES)

    def test_stage_observation_is_bounded_memory(self):
        from pingoo_tpu.engine.service import ServiceStats

        stats = ServiceStats()
        for i in range(100_000):  # the old list grew to 65536 floats
            stats.wait_hist.observe(i % 7)
        assert stats.wait_hist.count >= 100_000
        assert len(stats.wait_hist.counts) == len(WAIT_BUCKETS_MS) + 1
        assert stats.snapshot()["verdict_p50_ms"] >= 1


class TestTrace:
    def test_trace_ids_unique_and_16_hex(self):
        ids = {new_trace_id() for _ in range(1000)}
        assert len(ids) == 1000
        for tid in list(ids)[:10]:
            assert len(tid) == 16
            int(tid, 16)

    def test_access_log_sampler_every_nth(self, caplog):
        sampler = AccessLogSampler("test-listener", sample_every=3)
        with caplog.at_level(logging.INFO, logger="pingoo_tpu.access"):
            logged = [sampler.maybe_log(
                trace_id=new_trace_id(), method="GET", path="/x",
                status=200, client_ip="127.0.0.1", duration_ms=1.2)
                for _ in range(9)]
        assert sum(logged) == 3
        rec = [r for r in caplog.records if r.name == "pingoo_tpu.access"]
        assert len(rec) == 3
        assert rec[0].fields["sampled_1_in"] == 3
        assert rec[0].fields["trace_id"]

    def test_sampler_disabled(self):
        sampler = AccessLogSampler("t", sample_every=0)
        assert not sampler.maybe_log(
            trace_id="x", method="GET", path="/", status=200,
            client_ip="1.2.3.4", duration_ms=0.1)

    def test_json_formatter_survives_non_json_fields(self):
        from pingoo_tpu.logging_utils import JsonFormatter

        record = logging.LogRecord("t", logging.INFO, "f.py", 1,
                                   "msg", (), None)
        record.fields = {"path": object()}  # not JSON-serializable
        line = JsonFormatter().format(record)
        assert json.loads(line)["message"] == "msg"
