"""Compact staging (ISSUE 15, docs/EXECUTOR.md "Compact staging").

Covers the tentpole's cap-soundness contract and the satellites:

  * Plan-derived caps: the compile pass's per-field dependent-depth
    scan, the pow2 rung quantization, the PINGOO_STAGING_DEPTH clamp,
    and the two-threshold overflow rule (cap at or above the plan's
    required depth -> threshold is the spec, exactly full mode's
    over-capacity rule; clamped below it -> every longer row reroutes
    through the interpreter backstop).
  * Packed one-copy dispatch: the PackedLayout byte map, the layout
    cache that keys XLA compiles by caps rung-tuple, and device-side
    decode (verdict.unpack_staged) bit-identical to the side arrays
    the host keeps.
  * Randomized full|compact verdict bit-identity across seeds and odd
    batch shapes at the verdict-program level, plus the pinned
    last-dependent-byte-exactly-at-cap case.
  * Sidecar end-to-end: full|compact served-verdict checksums through
    real shm rings (ring wraparound, spill slots, megastep windows)
    and a mid-run hot-swap onto a plan with WIDER caps.
  * The megastep CostModel compile-poisoning fix (first (K, bucket)
    observation absorbed, never seeding the EWMA) and the
    staged-bytes-bucketed dispatch EWMA.
  * The analyze-lint hot registration of the packed encode path, with
    a mutation proof that a fresh per-batch allocation there fails
    `make analyze`.
"""

import os
import random
import threading
import time

import numpy as np
import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.compiler.plan import STAGING_RUNGS, quantize_stage_cap
from pingoo_tpu.engine.batch import (
    STRING_FIELDS,
    PackedLayout,
    RequestTuple,
    StagingEncoder,
    build_packed_layout,
    pow2_batch_size,
    resolve_stage_caps,
    resolve_staging_mode,
    stage_overflow_thresholds,
)
from pingoo_tpu.sched.scheduler import CostModel, _pow2_kb_bucket
from test_parity import LISTS, RULE_SOURCES, make_rules, random_requests

HAVE_NATIVE = native_ring.ensure_built()
needs_native = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native toolchain unavailable")


def _make_plan(sources=None, lists=None):
    return compile_ruleset(make_rules(sources or RULE_SOURCES),
                           LISTS if lists is None else lists)


def _rule(name, src):
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.expr import compile_expression

    return RuleConfig(name=name, actions=(Action.BLOCK,),
                      expression=compile_expression(src))


# -- plan-derived caps -------------------------------------------------------


class TestCapDerivation:
    def test_rung_quantization(self):
        assert STAGING_RUNGS == (16, 32, 64, 128, 256, 512, 1024, 2048)
        assert quantize_stage_cap(1, 2048) == 16
        assert quantize_stage_cap(16, 2048) == 16
        assert quantize_stage_cap(17, 2048) == 32
        assert quantize_stage_cap(300, 2048) == 512
        # The spec bounds the ladder: never stage wider than the field.
        assert quantize_stage_cap(300, 256) == 256
        assert quantize_stage_cap(4096, 2048) == 2048

    def test_shallow_plan_derives_shallow_caps(self):
        """A prefix rule depends on |pattern| bytes: the cap lands on
        the smallest rung covering it, far below the 2048 spec."""
        plan = compile_ruleset(
            [_rule("p", 'http_request.path.starts_with("/admin/")')], {})
        assert plan.staging_required["path"] <= 16
        assert plan.staging_caps["path"] == 16
        # Fields no rule reads stage at the minimum rung.
        assert plan.staging_caps["user_agent"] == STAGING_RUNGS[0]

    def test_regex_pins_field_to_spec(self):
        """An NFA scan can depend on any byte up to the scan window:
        the compile pass must pin the field to its full spec."""
        plan = _make_plan()  # RULE_SOURCES carries regex/contains rules
        specs = plan.field_specs
        assert plan.staging_caps["url"] == specs["url"]

    def test_resolve_mode_and_env_clamp(self, monkeypatch):
        plan = _make_plan()
        monkeypatch.delenv("PINGOO_STAGING", raising=False)
        assert resolve_staging_mode() == "full"
        assert resolve_stage_caps(plan) is None  # full = no caps
        monkeypatch.setenv("PINGOO_STAGING", "compact")
        monkeypatch.delenv("PINGOO_STAGING_DEPTH", raising=False)
        caps = resolve_stage_caps(plan)
        assert caps is not None
        for field in STRING_FIELDS:
            assert 1 <= caps[field] <= plan.field_specs.get(field, 256)
        monkeypatch.setenv("PINGOO_STAGING_DEPTH", "64")
        clamped = resolve_stage_caps(plan)
        assert all(clamped[f] <= max(64, 2) for f in STRING_FIELDS)

    def test_overflow_thresholds_two_regimes(self, monkeypatch):
        plan = _make_plan()
        monkeypatch.setenv("PINGOO_STAGING", "compact")
        monkeypatch.delenv("PINGOO_STAGING_DEPTH", raising=False)
        caps = resolve_stage_caps(plan)
        th = stage_overflow_thresholds(plan, caps)
        # Unclamped: every cap covers the plan's required depth, so the
        # thresholds equal the specs — overflow is full mode's rule.
        for field in STRING_FIELDS:
            assert th[field] == plan.field_specs.get(field, 256), field
        monkeypatch.setenv("PINGOO_STAGING_DEPTH", "64")
        caps64 = resolve_stage_caps(plan)
        th64 = stage_overflow_thresholds(plan, caps64)
        clamped_fields = [f for f in STRING_FIELDS
                         if caps64[f] < min(plan.staging_required.get(
                             f, 10**9), plan.field_specs.get(f, 256))]
        assert clamped_fields  # the regex-pinned url/path must clamp
        for field in clamped_fields:
            assert th64[field] == caps64[field], field


# -- packed layout + device decode ------------------------------------------


class TestPackedLayout:
    CAPS = {"host": 32, "url": 64, "path": 32, "method": 16,
            "user_agent": 32, "country": 2}

    def test_layout_geometry(self):
        layout = build_packed_layout(self.CAPS)
        off = 0
        for field, f_off, w in layout.fields:
            assert f_off == off and w == self.CAPS[field]
            off += w
        for _field, l_off in layout.lens:
            assert l_off == off
            off += 2
        assert layout.ip_off == off
        assert layout.asn_off == off + 16
        assert layout.port_off == off + 24
        assert layout.width == off + 32

    def test_layout_cache_reuses_hash_equal_instances(self):
        """Hot-swaps between plans on the same rungs must hand the
        jitted packed fns the SAME static layout (no retrace)."""
        a = build_packed_layout(dict(self.CAPS))
        b = build_packed_layout(dict(self.CAPS))
        assert a is b
        assert isinstance(a, PackedLayout) and hash(a) == hash(b)

    def test_device_decode_matches_host_arrays(self):
        """unpack_staged over a packed batch must reproduce the side
        arrays byte-for-byte — lens, big-endian IP words and the i64
        asn/port included (negative asn exercises the bitcast)."""
        from pingoo_tpu.engine.verdict import unpack_staged

        enc = StagingEncoder(16, stage_caps=self.CAPS)
        reqs = [
            RequestTuple(host="h.example", url="/x" * 40, path="/deep",
                         method="POST", user_agent="UA " + "y" * 50,
                         ip="203.0.113.9", remote_port=443,
                         asn=-64500, country="DE"),
            RequestTuple(host="b", url="/", path="/", ip="::1",
                         remote_port=65535, asn=2 ** 40, country="FR"),
        ]
        batch = enc.encode_requests(reqs, pad_to=4)
        assert batch.packed is not None
        dec = unpack_staged(np.asarray(batch.packed), batch.layout)
        for key, host_arr in batch.arrays.items():
            got = np.asarray(dec[key])
            want = np.asarray(host_arr)
            if key.endswith("_len"):
                # Device lens are exact only up to u16 (spec <= 2048).
                want = want.astype(np.int32)
            assert np.array_equal(got, want), key

    def test_staged_bytes_accounting(self):
        caps_enc = StagingEncoder(16, stage_caps=self.CAPS)
        full_enc = StagingEncoder(16)
        reqs = [RequestTuple(host="h", url="/" + "a" * 900, path="/p",
                             user_agent="ua", ip="10.0.0.1")]
        packed = caps_enc.encode_requests(reqs, pad_to=1)
        full = full_enc.encode_requests(reqs, pad_to=1)
        assert packed.staged_bytes == build_packed_layout(self.CAPS).width
        assert full.staged_bytes == sum(
            a.nbytes for a in full.arrays.values())
        # The long-URL row bucketed full mode to 1024 url columns; the
        # capped packed row stays at the layout stride.
        assert packed.staged_bytes < full.staged_bytes


# -- full|compact verdict bit-identity --------------------------------------


def _packed_batch(plan, reqs, pad, monkeypatch, depth=None):
    monkeypatch.setenv("PINGOO_STAGING", "compact")
    if depth is None:
        monkeypatch.delenv("PINGOO_STAGING_DEPTH", raising=False)
    else:
        monkeypatch.setenv("PINGOO_STAGING_DEPTH", str(depth))
    caps = resolve_stage_caps(plan)
    enc = StagingEncoder(
        max(64, pad), plan.field_specs, stage_caps=caps,
        overflow_thresholds=stage_overflow_thresholds(plan, caps))
    return enc.encode_requests(reqs, pad_to=pad)


class TestVerdictBitIdentity:
    """make_packed_verdict_fn over the packed buffer vs make_verdict_fn
    over full staging arrays: the device matrices must be bit-equal."""

    def _matrices(self, plan, reqs, pad, monkeypatch, depth=None):
        import jax

        from pingoo_tpu.engine.verdict import (
            make_packed_prefilter_fn,
            make_packed_verdict_fn,
            make_prefilter_fn,
            make_verdict_fn,
        )

        tables = jax.device_put(plan.device_tables())
        full_enc = StagingEncoder(max(64, pad), plan.field_specs)
        full = full_enc.encode_requests(reqs, pad_to=pad)
        dev_arrays = {k: jax.device_put(v) for k, v in full.arrays.items()}
        pf = make_prefilter_fn(plan)
        pf_hits = pf.fn(tables, dev_arrays)[0] if pf is not None else None
        ref = np.asarray(make_verdict_fn(plan)(
            tables, dev_arrays, pf_hits))
        self._full_overflow = np.asarray(full.overflow, dtype=bool)

        batch = _packed_batch(plan, reqs, pad, monkeypatch, depth=depth)
        assert batch.packed is not None
        dev_packed = jax.device_put(batch.packed)
        ppf = make_packed_prefilter_fn(plan)
        p_hits = (ppf.fn(tables, dev_packed, batch.layout)[0]
                  if ppf is not None else None)
        got = np.asarray(make_packed_verdict_fn(plan)(
            tables, dev_packed, batch.layout, p_hits))
        return ref, got, batch

    def test_random_rulesets_and_seeds(self, monkeypatch):
        plan = _make_plan()
        for seed, n in ((0, 7), (1, 13), (2, 31), (3, 64)):
            reqs = random_requests(random.Random(seed), n)
            pad = pow2_batch_size(n, 64)
            ref, got, batch = self._matrices(plan, reqs, pad, monkeypatch)
            assert np.array_equal(ref, got), (seed, n)
            # Unclamped caps: overflow is exactly full mode's over-spec
            # rule — no extra depth-overflow rows.
            assert np.array_equal(np.asarray(batch.overflow, dtype=bool),
                                  self._full_overflow), (seed, n)

    def test_clamped_caps_stay_identical_off_overflow_rows(
            self, monkeypatch):
        """Under a hard 64-byte clamp the unflagged rows must still be
        bit-identical (cap-decidability); flagged rows are the
        interpreter backstop's job and are excluded here."""
        plan = _make_plan()
        reqs = random_requests(random.Random(11), 48)
        ref, got, batch = self._matrices(plan, reqs, 64, monkeypatch,
                                         depth=64)
        clean = ~np.asarray(batch.overflow[:48], dtype=bool)
        assert clean.any()
        assert np.array_equal(ref[:48][clean], got[:48][clean])

    def test_last_dependent_byte_exactly_at_cap(self, monkeypatch):
        """Pinned boundary case: a 16-byte prefix pattern derives a
        16-byte cap; a row whose match is decided BY byte 15 (and a
        near-miss whose first divergence is byte 15) must verdict
        identically when the staged width is exactly 16."""
        pat = "/abcdefghijklmn/"  # 16 bytes
        plan = compile_ruleset(
            [_rule("edge", f'http_request.path.starts_with("{pat}")')],
            {})
        assert plan.staging_caps["path"] == 16
        reqs = [
            RequestTuple(path=pat + "tail/x", url=pat, ip="10.0.0.1"),
            RequestTuple(path=pat[:-1] + "X" + "tail", url="/",
                         ip="10.0.0.2"),
            RequestTuple(path=pat, url="/", ip="10.0.0.3"),
        ]
        ref, got, batch = self._matrices(plan, reqs, 4, monkeypatch)
        assert np.array_equal(ref, got)
        assert ref[:3, 0].tolist() == [True, False, True]
        assert not batch.overflow[:3].any()


# -- encoder overflow + hot-swap cap flips -----------------------------------


class TestPackedEncoder:
    def test_depth_overflow_flags_only_clamped_fields(self, monkeypatch):
        plan = _make_plan()
        monkeypatch.setenv("PINGOO_STAGING", "compact")
        monkeypatch.setenv("PINGOO_STAGING_DEPTH", "64")
        caps = resolve_stage_caps(plan)
        th = stage_overflow_thresholds(plan, caps)
        enc = StagingEncoder(8, plan.field_specs, stage_caps=caps,
                             overflow_thresholds=th)
        reqs = [
            RequestTuple(host="h", url="/" + "q" * 200, path="/short",
                         ip="10.0.0.1"),
            RequestTuple(host="h", url="/ok", path="/ok",
                         ip="10.0.0.2"),
        ]
        batch = enc.encode_requests(reqs, pad_to=2)
        assert batch.overflow[:2].tolist() == [True, False]
        # TRUE length rides the meta tail even though bytes are capped.
        assert int(batch.arrays["url_len"][0]) == 201
        assert batch.arrays["url_bytes"].shape[1] == caps["url"]

    def test_set_stage_caps_widens_at_flip(self):
        # Caps are clamped to each field's spec at install time (e.g.
        # method's spec is below 64): compare against the encoder's
        # APPLIED caps, and assert the url region genuinely widened.
        caps16 = {f: 16 if f != "country" else 2 for f in STRING_FIELDS}
        caps64 = {f: 64 if f != "country" else 2 for f in STRING_FIELDS}
        enc = StagingEncoder(8, stage_caps=caps16)
        r = [RequestTuple(url="/" + "z" * 60, path="/p", ip="10.0.0.1")]
        narrow = enc.encode_requests(r, pad_to=1)
        assert narrow.layout.width == build_packed_layout(caps16).width
        assert int(narrow.arrays["url_bytes"].shape[1]) == 16
        enc.set_stage_caps(caps64)
        wide = enc.encode_requests(r, pad_to=1)
        assert wide.layout.width == \
            build_packed_layout(enc.stage_caps).width
        assert wide.layout.width > narrow.layout.width
        assert int(wide.arrays["url_bytes"].shape[1]) == 64
        # The widened view carries the bytes the narrow one clipped.
        assert bytes(wide.arrays["url_bytes"][0][:61]) == \
            b"/" + b"z" * 60

    def test_encoder_without_packed_buffers_rejects_caps(self):
        enc = StagingEncoder(8)
        with pytest.raises(ValueError):
            enc.set_stage_caps({f: 16 for f in STRING_FIELDS})


# -- sidecar end-to-end ------------------------------------------------------


@needs_native
@pytest.mark.slow
class TestSidecarStagingParity:
    """PINGOO_STAGING full|compact through real shm rings: identical
    served actions over a stream that exercises ring wraparound, spill
    slots (over-spec URLs) and — in the megastep arm — K-slice
    windows, plus a mid-run hot-swap onto a plan with wider caps."""

    def _drive(self, tmp_path, tag, env, n=260):
        from pingoo_tpu.native_ring import Ring, RingSidecar

        plan = compile_ruleset(make_rules(RULE_SOURCES[:23]), LISTS)
        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            ring = Ring(str(tmp_path / f"ring-{tag}"), capacity=64,
                        create=True)  # small: forces wraparound
            sidecar = RingSidecar(ring, plan, LISTS, max_batch=16,
                                  pipeline_depth=3)
            th = threading.Thread(target=sidecar.run, daemon=True)
            th.start()
            rng = random.Random(31)
            paths = []
            for k in range(n):
                r = rng.random()
                if r < 0.25:
                    paths.append(b"/admin/.env")
                elif r < 0.30:  # over-spec url -> TRUNCATED+spill slot
                    paths.append(b"/long/" + b"a" * 4000)
                elif r < 0.40:  # in-spec but beyond a 64-byte clamp
                    paths.append(b"/mid/" + b"m" * 150)
                else:
                    paths.append(f"/ok/{k}".encode())
            actions = {}
            sent = 0
            deadline = time.time() + 120
            while len(actions) < n and time.time() < deadline:
                if sent < n:
                    p = paths[sent]
                    t = ring.enqueue(
                        method=b"GET", host=b"h.test", path=p, url=p,
                        user_agent=b"Mozilla/5.0 t",
                        ip=b"\x00" * 10 + b"\xff\xff" + bytes(
                            [172, 16, sent % 256, 7]),
                        port=4100 + sent, asn=64496, country=b"FR")
                    if t is not None:
                        sent += 1
                v = ring.poll_verdict()
                while v is not None:
                    actions[v[0]] = v[1]
                    v = ring.poll_verdict()
            parity = sidecar.parity
            if parity is not None:
                parity.flush(30)
                checked = parity.checked_total.value
                mismatches = parity.mismatch_total.value
            else:
                checked = mismatches = 0
            overflow_rows = sidecar.depth_overflow_rows
            sidecar.stop()
            ring.close()
            assert len(actions) == n, f"{tag}: {len(actions)}/{n}"
            return ([actions[t] for t in sorted(actions)],
                    checked, mismatches, overflow_rows)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_full_compact_checksum_parity_with_auditor(self, tmp_path):
        base = {"PINGOO_PIPELINE": "on", "PINGOO_PARITY_SAMPLE": "1",
                "PINGOO_PROVENANCE": "1"}
        full, chk_f, mm_f, _ = self._drive(
            tmp_path, "full", {**base, "PINGOO_STAGING": "full"})
        compact, chk_c, mm_c, _ = self._drive(
            tmp_path, "compact", {**base, "PINGOO_STAGING": "compact"})
        assert full == compact
        assert len(set(full)) > 1  # mixed allow/block stream
        assert chk_f > 0 and mm_f == 0
        assert chk_c > 0 and mm_c == 0

    def test_clamped_compact_stays_identical(self, tmp_path):
        """PINGOO_STAGING_DEPTH below the plan's regex pins: the
        interpreter backstop re-serves the deep rows and the served
        stream stays bit-identical to full mode."""
        base = {"PINGOO_PIPELINE": "on"}
        full, _, _, _ = self._drive(
            tmp_path, "full64", {**base, "PINGOO_STAGING": "full"})
        compact, _, _, over = self._drive(
            tmp_path, "comp64",
            {**base, "PINGOO_STAGING": "compact",
             "PINGOO_STAGING_DEPTH": "64"})
        assert full == compact
        assert over > 0  # the clamp actually rerouted deep rows

    def test_compact_megastep_windows_identical(self, tmp_path):
        base = {"PINGOO_PIPELINE": "on", "PINGOO_MEGASTEP": "force",
                "PINGOO_MEGASTEP_K": "4"}
        full, _, _, _ = self._drive(
            tmp_path, "mfull", {**base, "PINGOO_STAGING": "full"})
        compact, _, _, _ = self._drive(
            tmp_path, "mcompact", {**base, "PINGOO_STAGING": "compact"})
        assert full == compact

    def test_hot_swap_widens_caps_mid_run(self, tmp_path, monkeypatch):
        """Swap from a shallow-cap plan to one whose rules need wider
        staging: the encoder re-caps at the batch boundary and the
        post-swap phase is bit-exact under the NEW plan."""
        from pingoo_tpu.native_ring import Ring, RingSidecar

        monkeypatch.setenv("PINGOO_STAGING", "compact")
        shallow = compile_ruleset(
            [_rule("blk", 'http_request.path.starts_with("/alpha")')], {})
        deep_pat = "/beta/" + "d" * 90  # needs a 128-rung path cap
        deep = compile_ruleset(
            [_rule("blk", f'http_request.path.starts_with("{deep_pat}")')],
            {})
        assert deep.staging_caps["path"] > shallow.staging_caps["path"]
        ring = Ring(str(tmp_path / "ring-swap"), capacity=128,
                    create=True)
        sidecar = RingSidecar(ring, shallow, {}, max_batch=16)
        n = 40

        def enq(i, phase):
            if i % 3 == 0:
                p = (b"/alpha/x" if phase == "a"
                     else deep_pat.encode() + b"/x")
            else:
                p = b"/ok/%d" % i
            return ring.enqueue(method=b"GET", host=b"r.test", path=p,
                                url=p, user_agent=b"Mozilla/5.0")

        def poll_all(need, timeout=120.0):
            got = {}
            end = time.monotonic() + timeout
            while len(got) < need and time.monotonic() < end:
                v = ring.poll_verdict()
                if v is None:
                    time.sleep(0.002)
                    continue
                got[v[0]] = v[1]
            return got

        try:
            worker = threading.Thread(target=sidecar.run, daemon=True)
            worker.start()
            for i in range(n):
                assert enq(i, "a") is not None
            got_a = poll_all(n)
            handle = sidecar.request_swap(deep)
            assert handle.wait(120) and handle.result == "ok"
            for i in range(n, 2 * n):
                assert enq(i, "b") is not None
            got_b = poll_all(n)
            sidecar.stop()
            worker.join(30)
            assert sorted(got_a) == list(range(n))
            assert sorted(got_b) == list(range(n, 2 * n))
            for i in range(n):
                assert got_a[i] & 3 == (1 if i % 3 == 0 else 0), i
            for i in range(n, 2 * n):
                assert got_b[i] & 3 == (1 if i % 3 == 0 else 0), i
        finally:
            sidecar.stop()
            ring.close()


# -- CostModel: megastep compile absorption + dispatch-bytes EWMA ------------


class TestCostModelStaging:
    def test_first_megastep_observation_absorbed(self):
        """Regression (ISSUE 15 satellite): the first (K, bucket)
        window pays the cold XLA compile — seeding the EWMA with it
        poisoned estimate_megastep for the whole run and starved K>1
        admission. It must land in the first-observation absorber."""
        cm = CostModel(max_batch=64)
        cm.observe_stage("dispatch", 32, 1.0)
        cm.observe_stage("compute", 32, 2.0)
        amortized = cm.estimate_megastep(4, 32)
        cm.observe_megastep(4, 32, 900.0)  # cold compile wall
        # Still the amortization model, NOT 900ms.
        assert cm.estimate_megastep(4, 32) == amortized
        snap = cm.snapshot()
        assert snap["megastep_first_ms"] == {"4x32": 900.0}
        assert snap["megastep_ewma_ms"] == {}
        # The first STEADY window seeds the EWMA.
        cm.observe_megastep(4, 32, 8.0)
        assert cm.estimate_megastep(4, 32) == 8.0
        cm.observe_megastep(4, 32, 10.0)
        assert amortized != 900.0
        assert 8.0 < cm.estimate_megastep(4, 32) < 10.0

    def test_absorption_is_per_shape(self):
        cm = CostModel(max_batch=64)
        cm.observe_megastep(4, 32, 500.0)
        cm.observe_megastep(2, 32, 400.0)  # different K: own absorber
        snap = cm.snapshot()
        assert set(snap["megastep_first_ms"]) == {"4x32", "2x32"}
        assert snap["megastep_ewma_ms"] == {}

    def test_dispatch_bytes_ewma_buckets(self):
        cm = CostModel(max_batch=64)
        assert _pow2_kb_bucket(40 * 1024) == _pow2_kb_bucket(60 * 1024)
        assert _pow2_kb_bucket(40 * 1024) != _pow2_kb_bucket(600 * 1024)
        cm.observe_stage("dispatch", 32, 5.0)
        # Same row count, different staged bytes: the bytes bucket wins
        # once observed, the row bucket covers the rest.
        cm.observe_dispatch_bytes(40 * 1024, 0.5)
        assert cm.estimate_dispatch(32, 40 * 1024) == 0.5
        assert cm.estimate_dispatch(32, 600 * 1024) == 5.0
        assert cm.estimate_dispatch(32, None) == 5.0
        cm.observe_dispatch_bytes(40 * 1024, 1.5)
        est = cm.estimate_dispatch(32, 40 * 1024)
        assert 0.5 < est < 1.5
        snap = cm.snapshot()
        assert list(snap["dispatch_bytes_ewma_ms"]) == \
            [f"{_pow2_kb_bucket(40 * 1024)}kb"]
        # Garbage observations are dropped, not crashed on.
        cm.observe_dispatch_bytes(0, 1.0)
        cm.observe_dispatch_bytes(1024, -1.0)


# -- obs + lint satellites ---------------------------------------------------


class TestStagingObs:
    def test_metrics_in_schema_inventory(self):
        from pingoo_tpu.obs import schema

        assert "pingoo_staged_bytes_total" in schema.STAGING_METRICS
        assert "pingoo_staging_field_cap" in schema.STAGING_METRICS
        assert set(schema.STAGING_METRICS) <= schema.all_metric_names()


class TestStagingLintRegistry:
    def test_packed_encode_registered_hot(self):
        from tools.analyze import lint_config

        for fn in (
            "pingoo_tpu/engine/batch.py::"
            "StagingEncoder._encode_requests_packed",
            "pingoo_tpu/engine/batch.py::"
            "StagingEncoder._encode_slots_packed",
            "pingoo_tpu/engine/batch.py::StagingEncoder._pack_meta",
        ):
            assert fn in lint_config.HOT_FUNCTIONS, fn

    def test_mutated_packed_encode_alloc_fails_lint(self):
        """Mutation proof: the packed encode fills ONE reused buffer;
        a fresh per-batch matrix there must fail the hot-alloc lint."""
        from tools.analyze import REPO_ROOT, lint

        with open(os.path.join(REPO_ROOT, "pingoo_tpu", "engine",
                               "batch.py")) as f:
            src = f.read()
        needle = ("        layout = self._layout\n"
                  "        W = layout.width\n"
                  "        pk = buf[\"packed\"][: P * W].reshape(P, W)")
        assert src.count(needle) == 2  # both packed fill paths
        mutated = src.replace(
            needle,
            needle + "\n        scratch = np.zeros((P, W))", 1)
        assert "scratch = np.zeros" in mutated
        findings, _ = lint.lint_source(mutated,
                                       "pingoo_tpu/engine/batch.py")
        assert any(f.rule == "hot-alloc" for f in findings), findings
