"""HTTP/2 end-to-end: listener serving h2 (prior knowledge + TLS ALPN)
and h2 upstream proxying — the reference serves h1+h2 via hyper auto
(http_listener.rs:276-278) and proxies h1/h2 upstream
(http_proxy_service.rs:54-71). Our h2 rides a ctypes binding to the
system libnghttp2 (host/h2.py)."""

import asyncio
import ssl

import pytest

from pingoo_tpu.host import h2 as h2mod

pytestmark = pytest.mark.skipif(not h2mod.available(),
                                reason="libnghttp2 unavailable")


class TestBinding:
    def test_in_memory_round_trip(self):
        reqs, resps = [], []
        server = h2mod.H2ServerSession(
            lambda sid, hdrs, body: reqs.append((sid, hdrs, body)))
        client = h2mod.H2ClientSession(
            lambda sid, hdrs, body, err: resps.append((sid, hdrs, body, err)))
        s1 = client.submit_request("GET", "http", "t.test", "/a?x=1",
                                   [("user-agent", "ua")])
        s2 = client.submit_request("POST", "http", "t.test", "/b",
                                   [("user-agent", "ua")], body=b"body-2")
        answered = set()
        for _ in range(8):
            out = client.pull()
            if out:
                assert server.feed(out)
            for sid, hdrs, body in reqs:
                if sid not in answered:
                    answered.add(sid)
                    server.submit_response(
                        sid, 200, [("x-echo", "1")],
                        b"resp:" + bytes(body) + dict(hdrs)[b":path"])
            back = server.pull()
            if back:
                assert client.feed(back)
            if len(resps) == 2:
                break
        by_sid = {s: (dict(h), bytes(b), e) for s, h, b, e in resps}
        assert by_sid[s1][0][b":status"] == b"200"
        assert by_sid[s1][1] == b"resp:/a?x=1"
        assert by_sid[s2][1] == b"resp:body-2/b"
        assert all(e == 0 for _, _, e in by_sid.values())


def _mk_listener(tmp_path, loop_runner, tls_context=None, upstream_h2=False):
    """HttpListener + verdict service + (h1 or h2) upstream."""
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import (
        Action,
        RuleConfig,
        ServiceConfig,
        Upstream,
    )
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.expr import compile_expression
    from pingoo_tpu.host.captcha import CaptchaManager
    from pingoo_tpu.host.httpd import HttpListener
    from pingoo_tpu.host.services import HttpProxyService

    async def boot():
        if upstream_h2:
            up_port = await _start_h2_upstream()
        else:
            async def handle(reader, writer):
                data = await reader.read(8192)
                first = data.split(b"\r\n", 1)[0]
                body = b"up:" + first
                writer.write(b"HTTP/1.1 200 OK\r\ncontent-length: " +
                             str(len(body)).encode() + b"\r\n\r\n" + body)
                await writer.drain()
                writer.close()

            up = await asyncio.start_server(handle, "127.0.0.1", 0)
            up_port = up.sockets[0].getsockname()[1]

        rules = [RuleConfig(
            name="waf", actions=(Action.BLOCK,),
            expression=compile_expression(
                'http_request.url.contains("evil")'))]
        routes = [("app", None)]
        plan = compile_ruleset(rules, {}, routes=routes)

        class Reg:
            def get_upstreams(self, name):
                return [Upstream(hostname="127.0.0.1", port=up_port,
                                 tls=False, ip="127.0.0.1",
                                 h2=upstream_h2)]

        svc = HttpProxyService(
            ServiceConfig(name="app", route=None,
                          http_proxy=(Upstream(hostname="127.0.0.1",
                                               port=up_port, tls=False,
                                               ip="127.0.0.1",
                                               h2=upstream_h2),)),
            Reg())
        verdict = VerdictService(plan, {}, use_device=False, max_wait_us=100)
        cap = CaptchaManager(jwks_path=str(tmp_path / "jwks.json"))
        lst = HttpListener("h2t", "127.0.0.1", 0, [svc], verdict, {},
                           plan.rules, cap, tls_context=tls_context,
                           route_indices=[plan.route_index["app"]])
        await verdict.start()
        await lst.bind()
        asyncio.ensure_future(lst.serve_forever())
        return lst

    return loop_runner.run(boot())


async def _start_h2_upstream() -> int:
    """h2 prior-knowledge upstream echoing :path (built on our own
    server session — the binding under test serves both sides)."""

    async def serve(reader, writer):
        pending = []
        session = h2mod.H2ServerSession(
            lambda sid, hdrs, body: pending.append((sid, hdrs, body)))
        try:
            while True:
                out = session.pull()
                if out:
                    writer.write(out)
                    await writer.drain()
                while pending:
                    sid, hdrs, body = pending.pop(0)
                    path = dict(hdrs).get(b":path", b"?")
                    session.submit_response(
                        sid, 200, [("x-proto", "h2-upstream")],
                        b"h2up:" + path + b":" + bytes(body))
                    out = session.pull()
                    if out:
                        writer.write(out)
                        await writer.drain()
                data = await reader.read(65536)
                if not data or not session.feed(data):
                    break
        except OSError:
            pass
        finally:
            session.close()
            writer.close()

    server = await asyncio.start_server(serve, "127.0.0.1", 0)
    return server.sockets[0].getsockname()[1]


async def _h2_get(port, path, ssl_ctx=None, server_hostname=None, body=b"",
                  method="GET"):
    conn = h2mod.H2UpstreamConnection("127.0.0.1", port)
    await conn.connect(ssl=ssl_ctx, server_hostname=server_hostname)
    try:
        return await asyncio.wait_for(
            conn.request(method, "t.test", path,
                         [("user-agent", "h2-test-ua")], body), 10)
    finally:
        await conn.close()


class TestH2Listener:
    def test_prior_knowledge_waf_path(self, tmp_path, loop_runner):
        lst = _mk_listener(tmp_path, loop_runner)

        async def flow():
            ok = await _h2_get(lst.bound_port, "/hello")
            blocked = await _h2_get(lst.bound_port, "/x?q=evil")
            return ok, blocked

        ok, blocked = loop_runner.run(flow())
        assert ok[0] == 200 and b"up:GET /hello" in ok[2]
        assert blocked[0] == 403

    def test_multiplexed_streams_one_connection(self, tmp_path, loop_runner):
        lst = _mk_listener(tmp_path, loop_runner)

        async def flow():
            conn = h2mod.H2UpstreamConnection("127.0.0.1", lst.bound_port)
            await conn.connect()
            try:
                results = await asyncio.gather(
                    conn.request("GET", "t.test", "/a",
                                 [("user-agent", "ua")]),
                    conn.request("GET", "t.test", "/b?x=evil",
                                 [("user-agent", "ua")]),
                    conn.request("GET", "t.test", "/c",
                                 [("user-agent", "ua")]),
                )
            finally:
                await conn.close()
            return results

        a, b, c = loop_runner.run(flow())
        assert a[0] == 200 and b"/a" in a[2]
        assert b[0] == 403
        assert c[0] == 200 and b"/c" in c[2]

    def test_h1_still_works_alongside(self, tmp_path, loop_runner):
        lst = _mk_listener(tmp_path, loop_runner)

        async def flow():
            r, w = await asyncio.open_connection("127.0.0.1", lst.bound_port)
            w.write(b"GET /h1 HTTP/1.1\r\nhost: t\r\nuser-agent: ua\r\n"
                    b"connection: close\r\n\r\n")
            data = await r.read()
            w.close()
            return data

        data = loop_runner.run(flow())
        assert data.startswith(b"HTTP/1.1 200") and b"up:GET /h1" in data

    def test_empty_ua_403_over_h2(self, tmp_path, loop_runner):
        lst = _mk_listener(tmp_path, loop_runner)

        async def flow():
            conn = h2mod.H2UpstreamConnection("127.0.0.1", lst.bound_port)
            await conn.connect()
            try:
                return await asyncio.wait_for(
                    conn.request("GET", "t.test", "/", []), 10)
            finally:
                await conn.close()

        status, _, _ = loop_runner.run(flow())
        assert status == 403


class TestH2OverTls:
    def test_alpn_h2_negotiated_and_served(self, tmp_path, loop_runner):
        from pingoo_tpu.host.tlsmgr import TlsManager

        mgr = TlsManager(str(tmp_path / "tls"))
        lst = _mk_listener(tmp_path, loop_runner,
                           tls_context=mgr.server_context())
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        ctx.set_alpn_protocols(["h2"])

        async def flow():
            return await _h2_get(lst.bound_port, "/tls-h2", ssl_ctx=ctx,
                                 server_hostname="t.test")

        status, headers, body = loop_runner.run(flow())
        assert status == 200 and b"up:GET /tls-h2" in body


class TestH2Upstream:
    def test_proxy_over_h2_prior_knowledge(self, tmp_path, loop_runner):
        """h1 client -> listener -> h2 upstream (the proxy speaks h2)."""
        lst = _mk_listener(tmp_path, loop_runner, upstream_h2=True)

        async def flow():
            r, w = await asyncio.open_connection("127.0.0.1", lst.bound_port)
            w.write(b"GET /via-h2?a=1 HTTP/1.1\r\nhost: t\r\n"
                    b"user-agent: ua\r\nconnection: close\r\n\r\n")
            data = await r.read()
            w.close()
            return data

        data = loop_runner.run(flow())
        assert data.startswith(b"HTTP/1.1 200")
        assert b"h2up:/via-h2?a=1" in data
        assert b"x-proto: h2-upstream" in data.lower()

    def test_h2_end_to_end_both_sides(self, tmp_path, loop_runner):
        """h2 client -> listener -> h2 upstream: h2 on BOTH hops."""
        lst = _mk_listener(tmp_path, loop_runner, upstream_h2=True)

        async def flow():
            return await _h2_get(lst.bound_port, "/both?x=2")

        status, headers, body = loop_runner.run(flow())
        assert status == 200 and b"h2up:/both?x=2" in body
