"""Bot-score head: feature extraction, training convergence, service wiring."""

import jax
import numpy as np

from pingoo_tpu.engine import encode_requests
from pingoo_tpu.models import botscore
from pingoo_tpu.utils.crs import generate_traffic


def test_features_shape_and_determinism():
    reqs = generate_traffic(64, seed=1)
    arrays = encode_requests(reqs).arrays
    f1 = np.asarray(botscore.extract_features(arrays))
    f2 = np.asarray(botscore.extract_features(arrays))
    assert f1.shape == (64, botscore.NUM_FEATURES)
    np.testing.assert_array_equal(f1, f2)
    assert np.isfinite(f1).all()


def test_training_separates_bot_traffic():
    """Train on labeled clean-vs-attack traffic; loss must drop and the
    head must rank attack traffic above clean on held-out data."""
    clean = generate_traffic(256, attack_fraction=0.0, seed=2)
    bots = generate_traffic(256, attack_fraction=1.0, seed=3)
    reqs = clean + bots
    labels = np.array([0.0] * 256 + [1.0] * 256, dtype=np.float32)
    arrays = encode_requests(reqs).arrays
    feats = botscore.extract_features(arrays)

    params = botscore.init_params(jax.random.PRNGKey(0))
    tx, train_step = botscore.make_train_step(1e-2)
    opt_state = tx.init(params)
    step = jax.jit(train_step)
    first_loss = None
    for _ in range(300):
        params, opt_state, loss = step(params, opt_state, feats, labels)
        if first_loss is None:
            first_loss = float(loss)
    assert float(loss) < first_loss * 0.6

    held_clean = encode_requests(
        generate_traffic(64, attack_fraction=0.0, seed=4)).arrays
    held_bot = encode_requests(
        generate_traffic(64, attack_fraction=1.0, seed=5)).arrays
    s_clean = float(np.mean(np.asarray(botscore.score(params, held_clean))))
    s_bot = float(np.mean(np.asarray(botscore.score(params, held_bot))))
    assert s_bot > s_clean + 0.1, (s_clean, s_bot)


def test_service_returns_scores(loop_runner):
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.config.schema import Action, RuleConfig
    from pingoo_tpu.engine.batch import RequestTuple
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.expr import compile_expression

    rules = [RuleConfig(name="r", actions=(Action.BLOCK,),
                        expression=compile_expression("false"))]
    plan = compile_ruleset(rules, {})
    params = botscore.init_params(jax.random.PRNGKey(1))
    svc = VerdictService(plan, {}, use_device=True, max_wait_us=100,
                         bot_score_params=params)

    async def flow():
        await svc.start()
        try:
            return await svc.evaluate(RequestTuple(path="/x", user_agent="UA"))
        finally:
            await svc.stop()

    verdict = loop_runner.run(flow())
    # The returned score must be the head's actual output (default-0.0
    # from a silently broken scorer must not pass).
    from pingoo_tpu.engine.batch import pad_batch

    batch = encode_requests([RequestTuple(path="/x", user_agent="UA")],
                            plan.field_specs)
    expected = float(np.asarray(
        botscore.score(params, pad_batch(batch, 8).arrays))[0])
    assert abs(verdict.bot_score - expected) < 1e-5
    assert svc.stats.score_errors == 0


def test_params_save_load_roundtrip(tmp_path):
    params = botscore.init_params(jax.random.PRNGKey(7))
    path = str(tmp_path / "bot.npz")
    botscore.save_params(params, path)
    restored = botscore.load_params(path)
    arrays = encode_requests(generate_traffic(8, seed=6)).arrays
    np.testing.assert_allclose(np.asarray(botscore.score(params, arrays)),
                               np.asarray(botscore.score(restored, arrays)),
                               rtol=1e-6)
