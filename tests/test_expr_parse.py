"""Lexer + parser tests for the expression language."""

import pytest

from pingoo_tpu.expr import CompileError, parse
from pingoo_tpu.expr import ast
from pingoo_tpu.expr.lexer import tokenize


class TestLexer:
    def test_operators(self):
        toks = tokenize("|| && == != <= >= < > + - * / % ! ( ) [ ] { } , . :")
        lexemes = [t.value for t in toks[:-1]]
        assert lexemes == [
            "||", "&&", "==", "!=", "<=", ">=", "<", ">", "+", "-", "*",
            "/", "%", "!", "(", ")", "[", "]", "{", "}", ",", ".", ":",
        ]

    def test_numbers(self):
        toks = tokenize("1 42 0x1F 3.5 1e3 2.5e-2")
        vals = [t.value for t in toks[:-1]]
        assert vals == [1, 42, 31, 3.5, 1000.0, 0.025]

    def test_strings_and_escapes(self):
        toks = tokenize(r'"a\"b" ' + r"'c\n' " + r'"\x41" "B"')
        vals = [t.value for t in toks[:-1]]
        assert vals == ['a"b', "c\n", "A", "B"]

    def test_bools_and_idents(self):
        toks = tokenize("true false http_request _x")
        assert [t.kind for t in toks[:-1]] == ["BOOL", "BOOL", "IDENT", "IDENT"]
        assert toks[0].value is True and toks[1].value is False

    def test_comments(self):
        toks = tokenize("1 // comment\n + 2")
        assert [t.value for t in toks[:-1]] == [1, "+", 2]

    def test_in_rejected(self):
        # Reference parity: rules/rules.rs:69-71 rejects the `in` operator.
        with pytest.raises(CompileError, match="unknown operator: in"):
            tokenize('"a" in ["a"]')

    def test_unterminated_string(self):
        with pytest.raises(CompileError, match="unterminated"):
            tokenize('"abc')

    def test_unknown_escapes_preserved(self):
        # Regex-heavy rule strings must survive: "\s" stays "\s".
        toks = tokenize(r'"union\s+select"')
        assert toks[0].value == "union\\s+select"

    def test_surrogate_escape_rejected(self):
        with pytest.raises(CompileError, match="surrogate"):
            tokenize(r'"\ud800"')

    def test_bad_char(self):
        with pytest.raises(CompileError, match="unexpected character"):
            tokenize("a @ b")


class TestParser:
    def test_empty_invalid(self):
        # Reference parity: rules/rules.rs:56-58.
        for src in ("", "   ", "\n"):
            with pytest.raises(CompileError, match="empty"):
                parse(src)

    def test_precedence(self):
        node = parse("1 + 2 * 3 == 7 && true || false")
        assert isinstance(node, ast.Logical) and node.op == "||"
        left = node.left
        assert isinstance(left, ast.Logical) and left.op == "&&"
        cmp_node = left.left
        assert isinstance(cmp_node, ast.Binary) and cmp_node.op == "=="
        add = cmp_node.left
        assert isinstance(add, ast.Binary) and add.op == "+"
        mul = add.right
        assert isinstance(mul, ast.Binary) and mul.op == "*"

    def test_member_and_index(self):
        node = parse('lists["blocked"].contains(client.ip)')
        assert isinstance(node, ast.Call) and node.func == "contains"
        assert isinstance(node.recv, ast.Index)
        assert isinstance(node.recv.obj, ast.Ident) and node.recv.obj.name == "lists"
        (arg,) = node.args
        assert isinstance(arg, ast.Member) and arg.attr == "ip"

    def test_method_chain(self):
        node = parse('http_request.path.starts_with("/.env")')
        assert isinstance(node, ast.Call) and node.func == "starts_with"
        assert isinstance(node.recv, ast.Member) and node.recv.attr == "path"

    def test_non_associative_relations(self):
        with pytest.raises(CompileError, match="non-associative"):
            parse("1 < 2 < 3")

    def test_array_and_map_literals(self):
        node = parse('[1, 2, 3]')
        assert isinstance(node, ast.ArrayLit) and len(node.items) == 3
        node = parse('{"a": 1, "b": 2}')
        assert isinstance(node, ast.MapLit) and len(node.entries) == 2

    def test_unary_chains(self):
        node = parse("!!true")
        assert isinstance(node, ast.Unary) and isinstance(node.operand, ast.Unary)
        # Negative numeric literals constant-fold (so i64::MIN is writable).
        node = parse("--1")
        assert isinstance(node, ast.Literal) and node.value == 1
        node = parse("-x")
        assert isinstance(node, ast.Unary)

    def test_trailing_garbage(self):
        with pytest.raises(CompileError, match="trailing"):
            parse("1 + 2 3")

    def test_unbalanced(self):
        with pytest.raises(CompileError):
            parse("(1 + 2")
        with pytest.raises(CompileError):
            parse("a[1")

    def test_free_function_call(self):
        node = parse("length(http_request.path)")
        assert isinstance(node, ast.Call) and node.recv is None
