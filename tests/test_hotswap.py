"""Epoch-switched ruleset hot-swap (ISSUE 11, docs/RESILIENCE.md).

Contract under test, on both engine planes: a new RulesetPlan compiled
AHEAD of the switch flips in atomically at a batch boundary — verdicts
admitted before the flip are bit-exact under the OLD plan, verdicts
admitted after are bit-exact under the NEW one, and no ticket is
dropped or double-posted across the boundary. The subprocess/storm end
of this lives in tools/chaos_smoke.py (PINGOO_CHAOS=swap_storm); here
the same protocol is driven in-process so tier-1 stays fast.
"""

import threading
import time

import pytest

from pingoo_tpu import native_ring
from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine.hotswap import TenantPlanStore
from pingoo_tpu.expr import compile_expression


def _has_jax():
    try:
        import jax  # noqa: F401
        return True
    except Exception:
        return False


needs_jax = pytest.mark.skipif(not _has_jax(), reason="jax unavailable")
needs_native = pytest.mark.skipif(not native_ring.ensure_built(),
                                  reason="native toolchain unavailable")


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in ("PINGOO_CHAOS", "PINGOO_DFA", "PINGOO_MESH",
                "PINGOO_SCHED_MODE", "PINGOO_PARITY_SAMPLE",
                "PINGOO_PIPELINE", "PINGOO_PIPELINE_DEPTH"):
        monkeypatch.delenv(var, raising=False)


def _plan(prefix: str, extra_rules: int = 0):
    """Plan that blocks path.starts_with(prefix); `extra_rules` pads
    with never-matching rules so the two epochs' plans differ in shape,
    not just content (table re-layout is part of the flip)."""
    rules = [RuleConfig(
        name=f"block-{prefix.strip('/')}", actions=(Action.BLOCK,),
        expression=compile_expression(
            f'http_request.path.starts_with("{prefix}")'))]
    for i in range(extra_rules):
        rules.append(RuleConfig(
            name=f"pad{i}", actions=(Action.BLOCK,),
            expression=compile_expression(
                f'http_request.path.starts_with("/never/{i}/")')))
    return compile_ruleset(rules, {})


def _want(path: str, epoch: int) -> int:
    """Expected action lane for `path` under the plan of `epoch`:
    epoch 0 serves the /alpha plan, every later epoch the /beta plan."""
    blocked = "/alpha" if epoch == 0 else "/beta"
    return 1 if path.startswith(blocked) else 0


# -- python plane: VerdictService.swap_plan -------------------------------


@needs_jax
class TestServiceSwap:
    def test_swap_flips_epoch_and_actions(self, loop_runner):
        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService

        async def go():
            service = VerdictService(_plan("/alpha"), {},
                                     use_device=True)
            await service.start()
            try:
                async def ask(path):
                    return await service.evaluate(RequestTuple(
                        path=path, url=path, user_agent="x"))

                va = await ask("/alpha/1")
                vb = await ask("/beta/1")
                assert (va.epoch, va.action) == (0, 1)
                assert (vb.epoch, vb.action) == (0, 0)

                res = await service.swap_plan(_plan("/beta", 3))
                assert res["epoch"] == 1
                assert res["tenant"] == "default"
                assert res["pause_ms"] >= 0
                assert service.ruleset_epoch == 1

                va = await ask("/alpha/2")
                vb = await ask("/beta/2")
                assert (va.epoch, va.action) == (1, 0)
                assert (vb.epoch, vb.action) == (1, 1)
            finally:
                await service.stop()

        loop_runner.run(go())

    def test_concurrent_evaluates_bit_exact_per_epoch(self, loop_runner):
        """Race a swap against a stream of in-flight evaluates: every
        verdict must carry an epoch, and its action must be exactly
        what THAT epoch's plan says for that path — the per-epoch
        attribution contract (Verdict.epoch)."""
        import asyncio

        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService

        paths = [("/alpha/%d" if i % 2 else "/beta/%d") % i
                 for i in range(48)]

        async def go():
            service = VerdictService(_plan("/alpha"), {},
                                     use_device=True, max_batch=8)
            await service.start()
            try:
                async def ask(path):
                    v = await service.evaluate(RequestTuple(
                        path=path, url=path, user_agent="x"))
                    return path, v

                # First wave is in flight (queued, batching, some on
                # device) when the swap sentinel joins the queue — the
                # flip has to drain them on the OLD plan.
                first = [asyncio.ensure_future(ask(p))
                         for p in paths[:24]]
                res = await service.swap_plan(_plan("/beta", 3))
                assert res["epoch"] == 1
                rest = [asyncio.ensure_future(ask(p))
                        for p in paths[24:]]
                results = await asyncio.gather(*first, *rest)
                epochs = set()
                for path, v in results:
                    assert v.epoch in (0, 1)
                    assert not v.degraded
                    assert v.action == _want(path, v.epoch), \
                        (path, v.epoch, v.action)
                    epochs.add(v.epoch)
                # The flip happened mid-stream: the wave admitted
                # before the sentinel rode epoch 0, the tail epoch 1.
                assert epochs == {0, 1}
            finally:
                await service.stop()

        loop_runner.run(go())

    def test_swap_exports_epoch_gauge_and_counter(self, loop_runner):
        from pingoo_tpu.engine.service import VerdictService
        from pingoo_tpu.obs import REGISTRY
        from pingoo_tpu.obs.schema import HOTSWAP_METRICS

        async def go():
            service = VerdictService(_plan("/alpha"), {},
                                     use_device=True)
            await service.start()
            try:
                await service.swap_plan(_plan("/beta"), tenant="acme")
            finally:
                await service.stop()

        loop_runner.run(go())
        gauge = REGISTRY.gauge(
            "pingoo_ruleset_epoch",
            HOTSWAP_METRICS["pingoo_ruleset_epoch"],
            labels={"plane": "python"})
        assert gauge.value >= 1
        counter = REGISTRY.counter(
            "pingoo_ruleset_swap_total",
            HOTSWAP_METRICS["pingoo_ruleset_swap_total"],
            labels={"plane": "python", "tenant": "acme",
                    "result": "ok"})
        assert counter.value >= 1


# -- sidecar plane: RingSidecar.request_swap ------------------------------


def _enq(ring, i, phase="alpha"):
    path = (b"/%s/%d" % (phase.encode(), i)) if i % 3 == 0 \
        else b"/ok/%d" % i
    return ring.enqueue(method=b"GET", host=b"r.test", path=path,
                        url=path, user_agent=b"Mozilla/5.0")


def _want_ring(i, phase_blocked):
    return 1 if (i % 3 == 0 and phase_blocked) else 0


def _poll_all(ring, need, timeout=120.0):
    got: dict = {}
    count = 0
    deadline = time.monotonic() + timeout
    while count < need and time.monotonic() < deadline:
        v = ring.poll_verdict()
        if v is None:
            time.sleep(0.002)
            continue
        t, a, _ = v
        got.setdefault(t, []).append(a)
        count += 1
    grace = time.monotonic() + 0.2
    while time.monotonic() < grace:
        v = ring.poll_verdict()
        if v is None:
            time.sleep(0.01)
            continue
        t, a, _ = v
        got.setdefault(t, []).append(a)
    return got


@needs_native
@needs_jax
class TestSidecarSwap:
    def test_swap_changes_ruleset_bit_exact_per_epoch(self, tmp_path):
        """Phase A tickets verdict under the /alpha plan, the swap
        lands, phase B tickets verdict under the /beta plan — zero
        lost, zero doubled, and each phase bit-exact under ITS plan
        (the storm smoke swaps identical plans; this is the stronger
        cross-plan version)."""
        from pingoo_tpu.native_ring import Ring, RingSidecar

        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, _plan("/alpha"), {}, max_batch=16)
        n = 24
        try:
            worker = threading.Thread(target=sidecar.run, daemon=True)
            worker.start()
            for i in range(n):
                assert _enq(ring, i, "alpha") is not None
            got_a = _poll_all(ring, n)

            handle = sidecar.request_swap(_plan("/beta", 3))
            assert handle.wait(120) and handle.result == "ok"
            assert handle.epoch == sidecar.ruleset_epoch >= 1
            assert handle.pause_ms >= 0

            for i in range(n, 2 * n):
                assert _enq(ring, i, "beta") is not None
            got_b = _poll_all(ring, n)
            sidecar.stop()
            worker.join(30)
            assert not worker.is_alive()

            assert sorted(got_a) == list(range(n))
            assert sorted(got_b) == list(range(n, 2 * n))
            for got in (got_a, got_b):
                assert all(len(a) == 1 for a in got.values())
            # Epoch 0: /alpha blocked, /beta not; epoch >=1: inverse.
            for i in range(n):
                assert got_a[i][0] & 3 == _want_ring(i, True), i
            for i in range(n, 2 * n):
                assert got_b[i][0] & 3 == _want_ring(i, True), i
            # swap pause recorded for bench_regress's p99 track.
            assert len(sidecar.swap_pauses_ms) == sidecar.ruleset_epoch
        finally:
            sidecar.stop()
            ring.close()

    def test_swap_under_parity_sampling(self, tmp_path, monkeypatch):
        """Swap storm with the ParityAuditor sampling 100% of batches:
        the interpreter shadow-checks every device verdict across the
        flip, so a half-installed table would surface as a parity
        mismatch, not just a wrong bit."""
        monkeypatch.setenv("PINGOO_PARITY_SAMPLE", "1")
        from pingoo_tpu.native_ring import Ring, RingSidecar

        plan = _plan("/alpha")
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, plan, {}, max_batch=8)
        n = 48
        try:
            worker = threading.Thread(target=sidecar.run, daemon=True)
            worker.start()
            handles = []
            for i in range(n):
                assert _enq(ring, i, "alpha") is not None
                if i and i % 12 == 0:
                    # Same compiled plan each swap: any verdict drift
                    # across the flips is a swap-protocol bug.
                    handles.append(sidecar.request_swap(plan))
                time.sleep(0.001)
            got = _poll_all(ring, n)
            for h in handles:
                assert h.wait(120) and h.result == "ok", h.result
            sidecar.stop()
            worker.join(30)
            assert not worker.is_alive()

            assert sorted(got) == list(range(n))
            assert all(len(a) == 1 for a in got.values())
            for i in range(n):
                assert got[i][0] & 3 == _want_ring(i, True), i
            assert sidecar.ruleset_epoch >= len(handles)
            assert sidecar.parity is not None
            assert sidecar.parity.flush(60)
            assert sidecar.parity.mismatch_total.value == 0
        finally:
            sidecar.stop()
            ring.close()

    def test_swap_while_ladder_demoted(self, tmp_path, monkeypatch):
        """A swap landing while the degradation ladder is serving a
        fallback rung must still apply cleanly, and the demoted rung
        must serve the NEW plan bit-exactly (docs/RESILIENCE.md: the
        ladder degrades the execution tier, never the ruleset)."""
        from pingoo_tpu.native_ring import Ring, RingSidecar

        monkeypatch.setenv("PINGOO_CHAOS", "xla_error:1")
        ring = Ring(str(tmp_path / "ring"), capacity=256, create=True)
        sidecar = RingSidecar(ring, _plan("/alpha"), {}, max_batch=16)
        monkeypatch.delenv("PINGOO_CHAOS")
        n = 16
        try:
            for i in range(n):
                assert _enq(ring, i, "alpha") is not None
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": n},
                                 daemon=True)
            t.start()
            got = _poll_all(ring, n)
            t.join(60)
            assert not t.is_alive()
            assert sidecar.ladder.demoted(), \
                "chaos fault did not demote — test premise broken"
            assert sorted(got) == list(range(n))

            handle = sidecar.request_swap(_plan("/beta", 3))
            for i in range(n, 2 * n):
                assert _enq(ring, i, "beta") is not None
            t = threading.Thread(target=sidecar.run,
                                 kwargs={"max_requests": n},
                                 daemon=True)
            t.start()
            got2 = _poll_all(ring, n)
            t.join(60)
            assert not t.is_alive()
            assert handle.wait(1) and handle.result == "ok"
            assert sorted(got2) == list(range(n, 2 * n))
            assert all(len(a) == 1 for a in got2.values())
            for i in range(n, 2 * n):
                assert got2[i][0] & 3 == _want_ring(i, True), i
        finally:
            sidecar.stop()
            ring.close()


# -- multi-tenant compile-ahead store -------------------------------------


class TestTenantPlanStore:
    def _rules(self, tenant: str, n: int = 2):
        return [RuleConfig(
            name=f"{tenant}-r{i}", actions=(Action.BLOCK,),
            expression=compile_expression(
                f'http_request.path.starts_with("/{tenant}/{i}/")'))
            for i in range(n)]

    def test_tenant_scoped_fingerprints(self, tmp_path):
        """IDENTICAL rules under different tenant keys must cache and
        fingerprint separately — tenant isolation in the artifact
        cache (compiler/cache.py)."""
        store = TenantPlanStore(cache_dir=str(tmp_path))
        shared = self._rules("shared", 3)
        tenants = ["acme", "globex", "initech", "umbrella"]
        entries = {t: store.prepare(t, shared, {}) for t in tenants}
        fps = {e.fingerprint for e in entries.values()}
        assert len(fps) == len(tenants)
        assert store.tenants() == sorted(tenants)
        assert store.total_rules() == 3 * len(tenants)
        for t in tenants:
            assert store.get(t) is entries[t]
            assert entries[t].plan.rule_names[0] == "shared-r0"
        assert store.get("nosuch") is None

    def test_failed_prepare_keeps_serving_plan(self, tmp_path):
        store = TenantPlanStore(cache_dir=str(tmp_path))
        good = store.prepare("acme", self._rules("acme"), {})
        with pytest.raises(Exception):
            store.prepare("acme", [object()], {})
        assert store.get("acme") is good

    def test_multi_tenant_scale_2k_rules(self, tmp_path):
        """ISSUE 11 floor: >=4 tenants, 2k+ rules total, every tenant's
        plan independently compiled/fingerprinted and swappable."""
        store = TenantPlanStore(cache_dir=str(tmp_path))
        tenants = ["acme", "globex", "initech", "umbrella"]
        for t in tenants:
            store.prepare(t, self._rules(t, 512), {})
        assert store.total_rules() == 2048
        assert len({store.get(t).fingerprint for t in tenants}) == 4
        # Re-prepare hits the tenant-scoped cache: same fingerprint,
        # fresh entry (the store always reflects the LAST good push).
        fp0 = store.get("acme").fingerprint
        again = store.prepare("acme", self._rules("acme", 512), {})
        assert again.fingerprint == fp0
        assert store.get("acme") is again

    @needs_jax
    def test_prepared_tenant_plan_swaps_into_service(self, tmp_path,
                                                     loop_runner):
        """End-to-end: store.prepare -> swap_plan, per-tenant epochs."""
        from pingoo_tpu.engine.batch import RequestTuple
        from pingoo_tpu.engine.service import VerdictService

        store = TenantPlanStore(cache_dir=str(tmp_path))
        acme = store.prepare("acme", self._rules("acme"), {})
        globex = store.prepare("globex", self._rules("globex"), {})

        async def go():
            service = VerdictService(acme.plan, acme.lists,
                                     use_device=True)
            await service.start()
            try:
                res = await service.swap_plan(
                    globex.plan, lists=globex.lists, tenant="globex")
                assert res["tenant"] == "globex"
                assert service.tenant == "globex"
                v = await service.evaluate(RequestTuple(
                    path="/globex/0/x", url="/globex/0/x",
                    user_agent="x"))
                assert v.action == 1 and v.epoch == res["epoch"]
                v = await service.evaluate(RequestTuple(
                    path="/acme/0/x", url="/acme/0/x", user_agent="x"))
                assert v.action == 0
            finally:
                await service.stop()

        loop_runner.run(go())
