"""pingoo-analyze static-analysis suite (tools/analyze, make analyze).

Covers the three passes themselves AND the acceptance mutations from
ISSUE 3: adding a field to pingoo_ring.h alone must fail the ABI
check; inserting a bare .item() into engine/verdict.py must fail the
hot-path lint.
"""

import copy
import os

import pytest

from tools.analyze import REPO_ROOT, abi, lint
from tools.analyze import native as analyze_native

HAVE_CXX = abi.compiler() is not None
needs_cxx = pytest.mark.skipif(not HAVE_CXX,
                               reason="no C++ compiler available")


# -- ABI/layout checker ------------------------------------------------------


class TestAbiChecker:
    def test_python_dtypes_match_golden(self):
        assert abi.diff_tables(abi.python_table(), abi.load_golden(),
                               "python", "golden") == []

    @needs_cxx
    def test_emitter_matches_golden_and_python(self):
        c = abi.emitter_table()
        assert c is not None
        assert abi.diff_tables(c, abi.load_golden(), "C", "golden") == []
        assert abi.diff_tables(c, abi.python_table(), "C", "python") == []

    def test_native_ring_constants_assert_against_golden(self):
        """The former hand-maintained 4688-byte comments are now
        constants; they must equal the golden table's compiler truth."""
        from pingoo_tpu import native_ring as nr

        golden = abi.load_golden()
        sizes = {name: s["size"] for name, s in golden["structs"].items()}
        assert nr.REQUEST_SLOT_SIZE == sizes["PingooRequestSlot"] == 4688
        assert nr.VERDICT_SLOT_SIZE == sizes["PingooVerdictSlot"]
        assert nr.RING_HEADER_SIZE == sizes["PingooRingHeader"]
        assert nr.TELEMETRY_BLOCK_SIZE == sizes["PingooRingTelemetry"]
        assert nr.SPILL_SLOT_SIZE == sizes["PingooSpillSlot"]
        assert nr.RING_FORMAT_VERSION == golden["format_version"]
        consts = golden["constants"]
        assert nr.TELEMETRY_WORDS == consts["PINGOO_TELEMETRY_WORDS"]
        assert nr.SPILL_NONE == consts["PINGOO_SPILL_NONE"]
        assert len(nr.WAIT_BUCKET_BOUNDS_MS) + 1 == \
            consts["PINGOO_WAIT_BUCKETS"]

    @needs_cxx
    def test_added_header_field_alone_fails(self, tmp_path):
        """ISSUE 3 acceptance mutation: a field added to pingoo_ring.h
        without touching the dtype or golden must fail the check."""
        header = os.path.join(REPO_ROOT, "pingoo_tpu", "native",
                              "pingoo_ring.h")
        with open(header) as f:
            src = f.read()
        marker = "  uint32_t asn;\n"
        assert marker in src
        (tmp_path / "pingoo_ring.h").write_text(
            src.replace(marker, marker + "  uint32_t intruder;\n"))
        mutated = abi.emitter_table(header_dir=str(tmp_path))
        assert mutated is not None
        drift = abi.diff_tables(mutated, abi.load_golden(), "C", "golden")
        assert drift, "mutated header must not match the golden"
        assert any("PingooRequestSlot" in d for d in drift)
        # ... and against the live python dtype, not just the golden.
        assert abi.diff_tables(mutated, abi.python_table(), "C", "python")

    def test_dtype_drift_alone_fails(self):
        """Moving or dropping a field on the PYTHON side must fail."""
        table = abi.python_table()
        moved = copy.deepcopy(table)
        slot = moved["structs"]["PingooRequestSlot"]
        field = next(f for f in slot["fields"] if f["name"] == "asn")
        field["offset"] += 2
        assert any("asn" in d for d in abi.diff_tables(
            moved, abi.load_golden(), "python", "golden"))

        dropped = copy.deepcopy(table)
        slot = dropped["structs"]["PingooRequestSlot"]
        slot["fields"] = [f for f in slot["fields"]
                          if f["name"] != "enq_ms"]
        assert any("enq_ms" in d and "missing" in d
                   for d in abi.diff_tables(dropped, abi.load_golden(),
                                            "python", "golden"))

    def test_constant_drift_fails(self):
        table = copy.deepcopy(abi.python_table())
        table["constants"]["PINGOO_SPILL_SLOTS"] = 128
        assert any("PINGOO_SPILL_SLOTS" in d for d in abi.diff_tables(
            table, abi.load_golden(), "python", "golden"))


# -- JAX hot-path linter -----------------------------------------------------


def _lint(source: str, path: str = "pingoo_tpu/engine/sample.py"):
    findings, _warnings = lint.lint_source(source, path)
    return findings


class TestHotPathLinter:
    def test_current_tree_is_clean(self):
        findings, warnings = lint.lint_paths()
        assert findings == [], "\n".join(str(f) for f in findings)
        assert warnings == [], "\n".join(warnings)

    def test_inserted_item_into_verdict_fails(self):
        """ISSUE 3 acceptance mutation: a bare .item() added to
        engine/verdict.py must fail the lint."""
        with open(os.path.join(REPO_ROOT, "pingoo_tpu", "engine",
                               "verdict.py")) as f:
            src = f.read()
        mutated = src + "\n\ndef _leak(x):\n    return x.item()\n"
        findings = _lint(mutated, "pingoo_tpu/engine/verdict.py")
        assert [f.rule for f in findings] == ["sync-item"]

    def test_tolist_and_device_get_flagged(self):
        findings = _lint("def f(x):\n"
                         "    import jax\n"
                         "    return x.tolist(), jax.device_get(x)\n")
        assert {f.rule for f in findings} == {"sync-tolist",
                                              "sync-device-get"}

    def test_block_until_ready_allowlist(self):
        body = "def f(dev):\n    dev.block_until_ready()\n"
        assert [f.rule for f in _lint(body)] == ["sync-block"]
        # The same call inside the blessed _await_device (the one
        # sanctioned sync primitive finish_batch / finish_megastep
        # route through) is allowed.
        blessed = "def _await_device(dev):\n    dev.block_until_ready()\n"
        assert _lint(blessed, "pingoo_tpu/engine/verdict.py") == []
        # finish_batch itself is no longer blessed — a direct sync
        # there must go through _await_device.
        direct = "def finish_batch(dev):\n    dev.block_until_ready()\n"
        assert [f.rule for f in
                _lint(direct, "pingoo_tpu/engine/verdict.py")] \
            == ["sync-block"]
        # getattr() spelling is caught too.
        indirect = ("def f(dev):\n"
                    "    b = getattr(dev, 'block_until_ready', None)\n")
        assert [f.rule for f in _lint(indirect)] == ["sync-block"]

    def test_hot_function_asarray_and_alloc(self):
        src = ("import numpy as np\n"
               "class VerdictService:\n"
               "    def _evaluate_sync(self, dev):\n"
               "        buf = np.zeros(8)\n"
               "        return np.asarray(dev), buf\n")
        rules = sorted(f.rule for f in
                       _lint(src, "pingoo_tpu/engine/service.py"))
        assert rules == ["hot-alloc", "sync-asarray-hot"]
        # Identical code outside a registered hot function is fine.
        cold = src.replace("_evaluate_sync", "offline_helper")
        assert _lint(cold, "pingoo_tpu/engine/service.py") == []

    def test_recompile_const_upload_and_hoist(self):
        captured = ("import jax\n"
                    "import jax.numpy as jnp\n"
                    "TABLE = [1, 2, 3]\n"
                    "def make():\n"
                    "    @jax.jit\n"
                    "    def f(x):\n"
                    "        return x + jnp.asarray(TABLE)\n"
                    "    return f\n")
        assert [f.rule for f in _lint(captured)] == \
            ["recompile-const-upload"]
        hoisted = ("import jax\n"
                   "import jax.numpy as jnp\n"
                   "TABLE = [1, 2, 3]\n"
                   "def make():\n"
                   "    table = jnp.asarray(TABLE)\n"
                   "    @jax.jit\n"
                   "    def f(x):\n"
                   "        return x + table\n"
                   "    return f\n")
        assert _lint(hoisted) == []

    def test_scalar_cast_of_dispatch_result(self):
        src = ("class S:\n"
               "    def g(self, t, a):\n"
               "        dev = self._verdict_fn(t, a)\n"
               "        return float(dev)\n")
        assert [f.rule for f in _lint(src)] == ["sync-scalar-cast"]

    def test_jit_inside_loop(self):
        src = ("import jax\n"
               "def f(fns):\n"
               "    out = []\n"
               "    for fn in fns:\n"
               "        out.append(jax.jit(fn))\n"
               "    return out\n")
        assert [f.rule for f in _lint(src)] == ["recompile-jit-in-loop"]

    def test_suppression_requires_reason(self):
        bare = "def f(x):\n    return x.item()  # pingoo: allow(sync-item)\n"
        rules = sorted(f.rule for f in _lint(bare))
        # Reasonless allow() suppresses nothing and is itself flagged.
        assert rules == ["suppression-missing-reason", "sync-item"]
        good = ("def f(x):\n"
                "    return x.item()  "
                "# pingoo: allow(sync-item): batch of one, cold path\n")
        assert _lint(good) == []

    def test_standalone_suppression_covers_next_line(self):
        src = ("def f(x):\n"
               "    # pingoo: allow(sync-item): documented cold path\n"
               "    return x.item()\n")
        assert _lint(src) == []

    def test_unknown_rule_flagged(self):
        src = "x = 1  # pingoo: allow(no-such-rule): whatever\n"
        assert [f.rule for f in _lint(src)] == \
            ["suppression-missing-reason"]

    def test_unused_suppression_is_a_finding(self):
        """ISSUE 18 satellite: a reasoned allow() that matches nothing
        is dead weight that would swallow the NEXT finding on its line
        — a stale-suppression FINDING now, not a warning."""
        src = "x = 1  # pingoo: allow(sync-item): nothing here\n"
        findings, warnings = lint.lint_source(src, "pingoo_tpu/x.py")
        assert [f.rule for f in findings] == ["stale-suppression"]
        assert warnings == []

    def test_used_suppression_is_not_stale(self):
        src = ("def f(x):\n"
               "    return x.item()  "
               "# pingoo: allow(sync-item): cold path\n")
        assert _lint(src) == []

    def test_unquantized_len_into_dispatch_flagged(self):
        src = ("class S:\n"
               "    def go(self, data, x):\n"
               "        return self._verdict_fn(data, len(x))\n")
        assert [f.rule for f in _lint(src)] == ["unbounded-compile-axis"]

    def test_shape_attr_into_dispatch_flagged(self):
        src = ("class S:\n"
               "    def go(self, data, a):\n"
               "        return self._lane_fn(data, a.shape[0])\n")
        assert [f.rule for f in _lint(src)] == ["unbounded-compile-axis"]

    def test_quantized_shape_arg_is_clean(self):
        src = ("class S:\n"
               "    def go(self, data, x):\n"
               "        return self._verdict_fn(\n"
               "            data, pow2_batch_size(len(x), 1024))\n")
        assert _lint(src) == []

    def test_walker_skips_pycache_and_binaries(self, tmp_path):
        base = tmp_path / "pingoo_tpu" / "engine"
        (base / "__pycache__").mkdir(parents=True)
        (base / "__pycache__" / "junk.py").write_text("x.item()\n")
        (base / "ok.py").write_text("x = 1\n")
        (base / "blob.pyc").write_bytes(b"\x00\x01")
        files = list(lint.iter_lint_files(repo_root=str(tmp_path)))
        assert files == [str(base / "ok.py")]


# -- clang-tidy baseline plumbing -------------------------------------------


class TestTidyBaseline:
    SAMPLE = (
        "pingoo_tpu/native/pingoo_ring.cc:45:3: warning: avoid thing"
        " [bugprone-foo]\n"
        "junk line without structure\n"
        "/usr/include/c++/10/bits/stl_vector.h:99:5: warning: sys hdr"
        " [bugprone-bar]\n"
        "pingoo_tpu/native/pingoo_ring.cc:45:3: warning: avoid thing"
        " [bugprone-foo]\n")

    def test_normalize_dedups_and_drops_system_headers(self):
        keys = analyze_native.normalize_tidy_output(self.SAMPLE)
        assert keys == [
            "pingoo_tpu/native/pingoo_ring.cc:bugprone-foo: avoid thing"]

    def test_diff_against_baseline(self):
        findings = ["a.cc:bugprone-x: one", "b.cc:concurrency-y: two"]
        fresh, stale = analyze_native.diff_against_baseline(
            findings, ["a.cc:bugprone-x: one", "c.cc:bugprone-z: gone"])
        assert fresh == ["b.cc:concurrency-y: two"]
        assert stale == ["c.cc:bugprone-z: gone"]

    def test_committed_baseline_parses(self):
        # Comments only today; entries must be normalized keys.
        for entry in analyze_native.load_baseline():
            assert ":" in entry
