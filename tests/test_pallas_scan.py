"""Fused Pallas NFA scan kernel: differential parity + strategy plumbing.

The kernel (ops/pallas_scan.py) must be BIT-IDENTICAL to the lax.scan
path (ops/nfa_scan.scan_chunk) — which the corpus parity suite already
pins to the interpreter oracle — under every structural variation:
single/pair stepping, cross-word carry + extra opt-propagation passes,
per-row offsets and negative-t warm-up (the halo split), odd chunk
lengths, and non-tile-multiple batches. Runs under interpret=True on
this chip-less host, i.e. the exact kernel program a TPU would execute.

Also covered here: the plan-time strategy selector (compiler/plan.py),
its round-trip through the ruleset artifact cache, the footprint-
extension pass (compiler/repat.extend_footprint), and the halo
partition (PINGOO_NFA_SPLIT).
"""

import random

import numpy as np
import pytest

from pingoo_tpu.compiler import compile_ruleset
from pingoo_tpu.compiler.nfa import build_bank, pattern_footprint, simulate
from pingoo_tpu.compiler.repat import (
    compile_regex,
    extend_footprint,
    has_unbounded_rep,
)
from pingoo_tpu.config.schema import Action, RuleConfig
from pingoo_tpu.engine import (
    RequestTuple,
    batch_to_contexts,
    encode_requests,
    evaluate_batch,
    make_verdict_fn,
)
from pingoo_tpu.expr import compile_expression
from pingoo_tpu.ops.nfa_scan import (
    bank_to_tables,
    halo_split_k,
    halo_split_scan,
    nfa_scan,
)

SEEDS = (7, 1234, 999983, 31337, 2026)


def _random_field_batch(rng, L, B, alphabet):
    data = np.zeros((B, L), dtype=np.uint8)
    lens = np.zeros(B, dtype=np.int32)
    for i in range(B):
        n = rng.randint(0, L)
        data[i, :n] = np.frombuffer(
            bytes(rng.choice(alphabet) for _ in range(n)), np.uint8)
        lens[i] = n
    return data, lens


class TestFusedKernelParity:
    def test_full_corpus_banks_all_seeds(self):
        """Pallas vs lax.scan on every NFA bank of CRS-style rulesets
        across the 5 differential seeds, with REAL traffic bytes —
        multi-word carry and extra-pass banks included (asserted)."""
        from pingoo_tpu.engine.batch import bucket_arrays
        from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

        saw_carry = saw_passes = False
        for seed in SEEDS:
            rules, lists = generate_ruleset(
                60, with_lists=True, list_sizes=(128, 32), seed=seed)
            plan = compile_ruleset(rules, lists)
            reqs = generate_traffic(96, lists=lists, seed=seed + 1,
                                    attack_fraction=0.4)
            arrays = bucket_arrays(encode_requests(reqs).arrays)
            for key, tables in plan.np_tables.items():
                if not key.startswith("nfa_") or "@" in key:
                    continue
                field = key[4:]
                data = arrays[f"{field}_bytes"]
                lens = arrays[f"{field}_len"]
                saw_carry |= tables.has_carry
                saw_passes |= tables.extra_passes > 0
                want = np.asarray(nfa_scan(tables, data, lens))
                for lookup in (None, "pair"):
                    got = np.asarray(nfa_scan(tables, data, lens,
                                              lookup=lookup,
                                              backend="pallas"))
                    np.testing.assert_array_equal(
                        got, want, err_msg=f"seed {seed} {key} {lookup}")
        assert saw_carry and saw_passes

    def test_halo_split_rows_on_pallas_backend(self):
        """The within-device halo split (stacked rows, per-row NEGATIVE
        t offsets) over the fused kernel — both steppings."""
        rng = random.Random(5)
        sources = [r"abc", "x" * 40, r"<svg[^>]{0,40}onload", r"\.php$",
                   "b" * 45 + "$", r"\babc\b", "e{0,60}f", r"qq"]
        patterns = []
        for src in sources:
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))
        assert tables.halo_ok
        L = 256
        data, lens = _random_field_batch(
            rng, L, 37, b"xab<svg>onload .phpeqcf")
        for i, p in enumerate([b"p" * 40 + b"x" * 40,
                               b"w" * 211 + b"b" * 45,
                               b"z" * 60 + b"<svg " + b"a" * 30 + b"onload",
                               b"q" * 250 + b"qq"]):
            data[i, :len(p)] = np.frombuffer(p, np.uint8)
            lens[i] = len(p)
        k = halo_split_k(tables, L)
        assert k > 1
        want = np.asarray(nfa_scan(tables, data, lens))
        for lookup in (None, "pair"):
            got = np.asarray(halo_split_scan(tables, data, lens, k,
                                             lookup=lookup,
                                             backend="pallas"))
            np.testing.assert_array_equal(got, want, err_msg=str(lookup))

    def test_odd_length_and_tiny_batch(self):
        """Odd Lc exercises the synthetic pad column's structural skip;
        B below one batch tile exercises row padding."""
        patterns = []
        for src in (r"ab", r"c$", r"^d", r"e+f"):
            patterns.extend(compile_regex(src))
        tables = bank_to_tables(build_bank(patterns))
        rng = random.Random(9)
        data, lens = _random_field_batch(rng, 7, 3, b"abcdef")
        data[0, :2] = np.frombuffer(b"ab", np.uint8)
        lens[0] = 7
        want = np.asarray(nfa_scan(tables, data, lens))
        got = np.asarray(nfa_scan(tables, data, lens, lookup="pair",
                                  backend="pallas"))
        np.testing.assert_array_equal(got, want)


class TestStrategySelection:
    RULES = [
        'http_request.url.matches("(?i)union\\s+select")',
        'http_request.path.contains("passwd")',
        'http_request.path.matches("^/(admin|wp-admin)")',
        'http_request.url.matches("%3[Cc]script")',
    ]

    def _plan(self):
        rules = [RuleConfig(name=f"r{i}", expression=compile_expression(s),
                            actions=(Action.BLOCK,))
                 for i, s in enumerate(self.RULES)]
        return rules, compile_ruleset(rules, {})

    def test_default_selection_recorded(self):
        _, plan = self._plan()
        assert plan.scan_plans, "nfa banks must carry scan plans"
        for key, entry in plan.scan_plans.items():
            assert entry.strategy.kind in ("scan", "pallas")
            assert entry.strategy.source == "default"

    def test_env_override_strategies_agree(self, monkeypatch):
        rules, plan = self._plan()
        batch = encode_requests(
            [RequestTuple(path=p, url=u)
             for p, u in [("/admin", "/?q=union  select"),
                          ("/etc/passwd", "/x"), ("/ok", "/%3Cscript")]])
        results = {}
        for mode in ("", "scan", "pair", "pallas", "pallas_single"):
            monkeypatch.setenv("PINGOO_SCAN_STRATEGY", mode)
            verdict_fn = make_verdict_fn(plan)
            results[mode] = evaluate_batch(
                plan, verdict_fn, plan.device_tables(), batch, {})
        base = results[""]
        for mode, got in results.items():
            np.testing.assert_array_equal(got, base, err_msg=mode)
        assert base[0, 0] and base[0, 2] and base[1, 1] and base[2, 3]

    def test_cache_round_trip_preserves_selection(self, tmp_path):
        """VERDICT criterion: the strategy selection is persisted in the
        ruleset artifact cache — including a measured re-selection."""
        from pingoo_tpu.compiler.cache import (
            compile_ruleset_cached,
            update_cached_plan,
        )
        from pingoo_tpu.compiler.plan import reselect_scan_strategies

        rules, _ = self._plan()
        cache_dir = str(tmp_path)
        plan1 = compile_ruleset_cached(rules, {}, cache_dir=cache_dir)
        plan2 = compile_ruleset_cached(rules, {}, cache_dir=cache_dir)
        assert plan2.scan_plans == plan1.scan_plans
        assert all(e.strategy.source == "default"
                   for e in plan2.scan_plans.values())

        # Autotune path: measured costs flip the selection; the updated
        # artifact must serve the measured choice on the next load.
        reselect_scan_strategies(
            plan1, {"scan": 1.0, "pair": 5.0, "pallas": 5.0,
                    "pallas_pair": 5.0})
        assert all(e.strategy == e.strategy.__class__(
            kind="scan", pair=False, halo_k=e.strategy.halo_k,
            source="measured", cost=1.0)
            for e in plan1.scan_plans.values())
        update_cached_plan(rules, {}, plan1, cache_dir)
        plan3 = compile_ruleset_cached(rules, {}, cache_dir=cache_dir)
        assert plan3.scan_plans == plan1.scan_plans
        assert all(e.strategy.source == "measured"
                   for e in plan3.scan_plans.values())

    def test_autotune_hook_produces_costs(self):
        """bench.autotune_scan_strategies measures every strategy kind
        on the live (CPU) backend and returns scan-relative costs."""
        import os as _os
        import sys as _sys

        _sys.path.insert(0, _os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))))
        from bench import autotune_scan_strategies

        rules, plan = self._plan()
        from pingoo_tpu.engine.batch import bucket_arrays

        reqs = [RequestTuple(path="/admin", url="/?q=union select")] * 16
        arrays = bucket_arrays(encode_requests(reqs).arrays)
        costs = autotune_scan_strategies(
            plan, plan.device_tables(), arrays, iters=2)
        assert costs.get("scan") == 1.0
        assert {"pair", "pallas", "pallas_pair"} <= set(costs)


class TestFootprintExtension:
    SOURCES = [r"ab+c", r"x[0-9]*y", r"(?i)union\s+select", r"'\s*--",
               r"a+b+c", r"\bor\b\s+1=1", r"onload\s*=", r"x+$", r"^a+b",
               r"\bword\b", r"q+"]

    def test_extension_exact_over_truncated_view(self):
        rng = random.Random(7)
        maxl = 24
        alpha = b"abcxy0union select'-=wordq19\t"
        for src in self.SOURCES:
            for lp in compile_regex(src):
                ext = extend_footprint(lp, maxl)
                assert ext is not None, src
                assert not has_unbounded_rep(ext), src
                for _ in range(150):
                    n = rng.randint(0, maxl)
                    s = bytes(rng.choice(alpha) for _ in range(n))
                    assert simulate(lp, s) == simulate(ext, s), (src, s)
                # saturating runs at the cap — the boundary the bound
                # must be exact at
                for s in (b"ab" + b"b" * 21 + b"c", b"q" * maxl,
                          b"x" + b"5" * 22 + b"y", b"'" + b" " * 21 + b"--"):
                    s = s[:maxl]
                    assert simulate(lp, s) == simulate(ext, s), (src, s)

    def test_extended_bank_is_halo_ok(self):
        pats = []
        for src in (r"ab+c", r"x[0-9]*y", r"abc"):
            for lp in compile_regex(src):
                ext = extend_footprint(lp, 24)
                assert ext is not None
                pats.append(ext)
        tables = bank_to_tables(build_bank(pats))
        assert tables.halo_ok
        # positions bounded by the 24-byte cap + guard/sticky bits
        assert tables.max_footprint <= 24 + 3
        assert all(pattern_footprint(p) <= 24 + 3 for p in pats)

    def test_split_plan_end_to_end_parity(self, monkeypatch):
        """PINGOO_NFA_SPLIT=1: url/path banks partition into a
        halo-splittable @short sub-bank + @rest residual; the recombined
        verdict stays exact against the interpreter oracle."""
        from pingoo_tpu.engine import RequestTuple
        from pingoo_tpu.engine.verdict import interpret_rules_row
        from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

        monkeypatch.setenv("PINGOO_NFA_SPLIT", "1")
        rules, lists = generate_ruleset(
            80, with_lists=True, list_sizes=(128, 32), seed=31337)
        plan = compile_ruleset(rules, lists)
        split_entries = [e for e in plan.scan_plans.values()
                         if e.split is not None]
        assert split_entries, "corpus must produce a partitioned bank"
        for entry in split_entries:
            short = plan.np_tables[entry.split[0]]
            assert short.halo_ok
            assert entry.short_strategy.halo_k > 1
        reqs = generate_traffic(64, lists=lists, seed=4, attack_fraction=0.4)
        batch = encode_requests(reqs)
        matched = evaluate_batch(plan, make_verdict_fn(plan),
                                 plan.device_tables(), batch, lists)
        for i, ctx in enumerate(batch_to_contexts(batch, lists)):
            want = interpret_rules_row(plan, ctx)
            assert np.array_equal(matched[i], want), i
