# Build/packaging (reference parity: Makefile `make build` / `make check`).

PY ?= python

.PHONY: all native test check bench clean

all: native

native:
	$(MAKE) -C pingoo_tpu/native

test: native
	$(PY) -m pytest tests/ -x -q

check:
	$(PY) -m compileall -q pingoo_tpu
	$(PY) -c "import pingoo_tpu.config, pingoo_tpu.compiler, pingoo_tpu.engine"

bench: native
	$(PY) bench.py

clean:
	$(MAKE) -C pingoo_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
