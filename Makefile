# Build/packaging (reference parity: Makefile `make build` / `make check`).

PY ?= python

.PHONY: all native test check bench bench-regress audit asan \
	metrics-smoke mesh-smoke chaos-smoke megastep-smoke body-smoke \
	staging-smoke timeline-smoke \
	clean analyze analyze-abi analyze-lint analyze-tidy analyze-tsan \
	fuzz prove ringcheck surface

all: native

native:
	$(MAKE) -C pingoo_tpu/native

test: native
	$(PY) -m pytest tests/ -x -q

check:
	$(PY) -m compileall -q pingoo_tpu
	$(PY) -c "import pingoo_tpu.config, pingoo_tpu.compiler, pingoo_tpu.engine"
	$(MAKE) analyze
	$(MAKE) mesh-smoke
	$(MAKE) chaos-smoke
	$(MAKE) megastep-smoke
	$(MAKE) body-smoke
	$(MAKE) staging-smoke
	$(MAKE) timeline-smoke

# Static analysis suite (docs/STATIC_ANALYSIS.md) — offline-safe; each
# pass skips with a warning when its toolchain is missing, and each is
# individually invocable. `analyze` also re-runs the metrics-schema
# audit so one target gates every machine-checked invariant:
#   analyze-abi   C++ header vs numpy dtypes vs committed golden layout
#   analyze-lint  JAX hot-path AST linter (host syncs, recompile
#                 hazards, hot-function allocation)
#   analyze-tidy  clang-tidy bugprone/concurrency vs tracked baseline
#   analyze-tsan  extended ring_stress under -fsanitize=thread
#   fuzz          differential HTTP-parsing fuzzer across all three
#                 parse paths (docs/FUZZING.md)
#   prove         lowering-soundness prover + compile surface +
#                 ring-protocol model checker (ISSUE 18; skips with a
#                 warning when jax is unavailable)
analyze: analyze-abi analyze-lint analyze-tidy analyze-tsan fuzz prove
	$(PY) tools/check_metrics_schema.py

analyze-abi:
	$(PY) -m tools.analyze abi

analyze-lint:
	$(PY) -m tools.analyze lint

analyze-tidy:
	$(PY) -m tools.analyze tidy

analyze-tsan:
	$(PY) -m tools.analyze tsan

# Machine-checked lowering soundness (ISSUE 18, docs/STATIC_ANALYSIS.md
# "Prove"): discharge every obligation on the seed 500-rule plan + the
# body plan, refresh COMPILE_SURFACE.json, model-check the ring
# protocol, and run the five mutation self-tests. Offline-safe.
prove:
	env JAX_PLATFORMS=cpu $(PY) -m tools.analyze prove

ringcheck:
	$(PY) -m tools.analyze ringcheck

surface:
	$(PY) -m tools.analyze surface

# Differential parsing fuzzer (ISSUE 11, docs/FUZZING.md): 5k seeded
# framing/encoding mutants through the native listener, the python
# listener's parse oracle, and interpreter field extraction; any
# non-documented divergence of RequestTuple fields or verdict bits
# fails. Deterministic, offline-safe (no native toolchain -> 2-path).
fuzz: native
	env JAX_PLATFORMS=cpu $(PY) -m tools.analyze fuzz

bench: native
	$(PY) bench.py

# Bench trajectory gate (ISSUE 5 satellite): `bench.py --history`
# appends each run to BENCH_history.jsonl; this compares the latest run
# against the previous comparable one (same backend) and fails on a
# >BENCH_REGRESS_THRESHOLD (default 10%) regression of any tracked
# metric.
bench-regress:
	$(PY) tools/bench_regress.py

# Dependency audit — the reference ships .github/workflows/audit.yml
# (cargo audit + cargo deny); the equivalent here is pip-audit over the
# Python environment plus the EXACT native runtime libraries the data
# plane links (the image has no dev packages to query, so surface the
# versioned sonames for CVE review). pip-audit needs network; when it
# is unavailable the target still emits the frozen dependency list for
# an offline scanner.
audit:
	@$(PY) -m pip_audit 2>/dev/null || \
		{ echo "pip-audit unavailable/offline; frozen deps for offline review:"; \
		  $(PY) -m pip freeze; }
	@echo "-- native plane runtime libraries --"
	@ldconfig -p | grep -E 'libssl|libcrypto|libnghttp2' || true
	@if [ -x pingoo_tpu/native/httpd ]; then \
		ldd pingoo_tpu/native/httpd | grep -E 'ssl|crypto|nghttp2'; fi
	@echo "-- metrics schema parity --"
	$(PY) tools/check_metrics_schema.py

# Mesh-serving smoke (ISSUE 6, docs/SCHEDULER.md): serve live requests
# through PINGOO_MESH=2x2x2 on 8 fake host devices, prove verdict
# bit-identity vs single-device + scheduler/deadline metrics export.
# Offline-safe: skips with a warning when jax is unavailable.
mesh-smoke:
	$(PY) tools/mesh_smoke.py

# Sidecar supervision chaos smoke (ISSUE 10, docs/RESILIENCE.md):
# SIGKILL the ring sidecar mid-batch and prove crash-reattach
# reconciliation (zero lost / double-posted tickets, bounded p99,
# bit-exact verdicts), heartbeat-freeze detection, and ladder demotion
# under injected device faults. Offline-safe: skips with a warning
# when jax or the native toolchain is unavailable.
chaos-smoke:
	$(PY) tools/chaos_smoke.py

# Device-resident megastep smoke (ISSUE 12, docs/EXECUTOR.md): prove
# PINGOO_MEGASTEP=force is bit-identical to the per-batch oracle on
# BOTH planes with real K>1 windows dispatched. Offline-safe: skips
# when jax is unavailable; the sidecar half skips without the native
# toolchain.
megastep-smoke:
	$(PY) tools/megastep_smoke.py

# Compact-staging smoke (ISSUE 15, docs/EXECUTOR.md "Compact
# staging"): prove PINGOO_STAGING=compact is bit-identical to the
# full-mode oracle on BOTH planes, with the ParityAuditor clean over
# the compact path and a nonzero staged-bytes saving on a long-URL
# stream. Offline-safe: skips when jax is unavailable; the sidecar
# half skips without the native toolchain.
staging-smoke:
	$(PY) tools/staging_smoke.py

# Perf-ledger + timeline smoke (ISSUE 17, docs/OBSERVABILITY.md): prove
# the compile ledger records the warm-up compiles (JSONL agreeing with
# the counters), sampled batch spans nest and export as Chrome-trace
# JSON with the cross-plane ring-wait join, the durable cost ledger
# round-trips EWMAs and discards stale fingerprints, and the record
# path costs <2% of a batch. Offline-safe: skips when jax is
# unavailable; the sidecar half skips without the native toolchain.
timeline-smoke:
	$(PY) tools/timeline_smoke.py

# Streaming body-inspection smoke (ISSUE 13, docs/BODY_STREAMING.md):
# prove stream==contiguous==oracle scanner parity with seams inside
# every match literal, the window-gap degrade lane, and the native
# httpd under PINGOO_BODY_INSPECT=on blocking torn-literal bodies
# (gate off = bit-exact status quo). Offline-safe: skips with a
# warning when jax is unavailable; the native half skips without g++.
body-smoke:
	$(PY) tools/body_smoke.py

# Live observability smoke: boot the native plane + ring sidecar + a
# Python listener, scrape both /__pingoo/metrics endpoints in both
# formats, and validate them against the documented inventory
# (docs/OBSERVABILITY.md / pingoo_tpu/obs/schema.py).
metrics-smoke: native
	env JAX_PLATFORMS=cpu $(PY) tools/metrics_smoke.py

# ASAN/UBSAN build of the native data plane (httpd_asan).
asan:
	$(MAKE) -C pingoo_tpu/native asan

clean:
	$(MAKE) -C pingoo_tpu/native clean
	find . -name __pycache__ -type d -exec rm -rf {} +
