#!/usr/bin/env python
"""Headline benchmark: WAF-evaluated requests/sec/chip @ 500 rules.

BASELINE.md north star: >= 1,000,000 req/s/chip on a 500-rule
OWASP-CRS-style ruleset at p99 added verdict latency < 2 ms (TPU v5e-1).
The reference publishes no numbers (BASELINE.md: `published` is {});
`vs_baseline` is measured against the 1M req/s target.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "req/s", "vs_baseline": N, ...}

Method: UNFILTERED 500-rule CRS-style ruleset (pingoo_tpu/utils/crs.py;
includes \\b and >31-position multi-word patterns — whatever the
compiler cannot lower is host-interpreted and reported via
`device_residency`) + 128k-entry IP blocklist + 4k ASN bitset;
replayed-log-style traffic at 5% attack rate. Timing uses a device-side
chained loop (each iteration's verdict feeds a carried checksum) with an
empty-loop floor subtracted: per-call wall timing is unreliable on
tunneled devices, where dispatch returns before execution completes. The
per-batch figure is therefore pure on-chip verdict time over the
device-resident rules; `p_batch_ms` is also the added verdict latency
for a full batch (the <2 ms budget).
"""

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    # 2048 keeps the full-batch verdict inside the 2 ms latency budget on
    # a v5e-1 while giving up only ~5% throughput vs 4096.
    batch_size = int(os.environ.get("BENCH_BATCH", "2048"))
    num_rules = int(os.environ.get("BENCH_RULES", "500"))
    iters = int(os.environ.get("BENCH_ITERS", "200"))

    import jax
    import jax.numpy as jnp

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine import encode_requests
    from pingoo_tpu.engine.batch import bucket_arrays
    from pingoo_tpu.engine.verdict import _eval_bool, _eval_leaves
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    dev = jax.devices()[0]
    t0 = time.time()
    rules, lists = generate_ruleset(
        num_rules, with_lists=True, list_sizes=(131072, 4096))
    plan = compile_ruleset(rules, lists)
    build_s = time.time() - t0
    residency = plan.stats["device_rules"] / plan.stats["rules"]
    device_rules = [r for r in plan.rules if not r.host]

    tables = jax.device_put(plan.device_tables(), dev)
    reqs = generate_traffic(batch_size, lists=lists, seed=100)
    arrays = jax.device_put(bucket_arrays(encode_requests(reqs).arrays), dev)

    def verdict_body(tables, arrays, salt):
        B = arrays["asn"].shape[0]
        a = dict(arrays)
        a["asn"] = a["asn"] + salt  # defeat cross-iteration CSE
        leaves = _eval_leaves(plan, tables, a, B)
        eff = [None] * len(plan.leaves)
        for leaf_id, (v, e) in leaves.items():
            eff[leaf_id] = v & ~e
        base = eff + [jnp.ones((B,), dtype=bool), jnp.zeros((B,), dtype=bool)]
        extra, rule_col = [], []
        from pingoo_tpu.compiler.lowering import BConst, BErrConst, BLeaf

        for rule in device_rules:
            if rule.always:
                rule_col.append(len(plan.leaves))
            elif isinstance(rule.ir, BLeaf):
                rule_col.append(rule.ir.leaf_id)
            elif isinstance(rule.ir, BConst):
                rule_col.append(len(plan.leaves) if rule.ir.value
                                else len(plan.leaves) + 1)
            elif isinstance(rule.ir, BErrConst):
                rule_col.append(len(plan.leaves) + 1)
            else:
                v, e = _eval_bool(rule.ir, leaves, B)
                rule_col.append(len(base) + len(extra))
                extra.append(v & ~e)
        allmat = jnp.stack(base + extra, axis=1)
        return jnp.take(allmat, jnp.asarray(rule_col, dtype=jnp.int32), axis=1)

    @jax.jit
    def run_n(tables, arrays, n):
        def body(i, acc):
            m = verdict_body(tables, arrays, acc % 2)
            return acc + m.sum().astype(jnp.int64)
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    @jax.jit
    def floor_loop(arrays, n):
        def body(i, acc):
            return acc + arrays["asn"].sum() + i
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    t0 = time.time()
    int(run_n(tables, arrays, 2))
    int(floor_loop(arrays, 2))
    compile_s = time.time() - t0

    t0 = time.time()
    int(floor_loop(arrays, iters))
    floor_a = time.time() - t0
    t0 = time.time()
    checksum = int(run_n(tables, arrays, iters))
    full = time.time() - t0
    t0 = time.time()
    int(floor_loop(arrays, iters))
    floor_b = time.time() - t0

    per_batch_s = (full - (floor_a + floor_b) / 2) / iters
    rps = batch_size / per_batch_s
    result = {
        "metric": "waf_requests_per_sec_per_chip_500rules",
        "value": round(rps, 1),
        "unit": "req/s",
        "vs_baseline": round(rps / 1_000_000.0, 4),
        "batch_size": batch_size,
        "rules": num_rules,
        "device_rules": plan.stats["device_rules"],
        "device_residency": round(residency, 4),
        "p_batch_ms": round(per_batch_s * 1000, 3),
        "latency_budget_ms": 2.0,
        "device": str(dev),
        "checksum": checksum,
        "build_s": round(build_s, 1),
        "compile_s": round(compile_s, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
