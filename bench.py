#!/usr/bin/env python
"""Headline benchmark: WAF-evaluated requests/sec/chip @ 500 rules.

BASELINE.md north star: >= 1,000,000 req/s/chip on a 500-rule
OWASP-CRS-style ruleset at p99 added verdict latency < 2 ms (TPU v5e-1).
The reference publishes no numbers (BASELINE.md: `published` is {});
`vs_baseline` is measured against the 1M req/s target.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "req/s", "vs_baseline": N, ...}

Besides the headline on-chip kernel number, the same line carries:
  * blocklist_*: BASELINE config 3 — membership lookups/s against a
    1M-entry IP/CIDR blocklist (sorted-prefix-bucket kernel,
    ops/cidr.py), measured with the same chained-loop method.
  * e2e_*: the COMMITTED end-to-end number — native loadgen_http ->
    native httpd -> shared-memory ring -> Python sidecar -> device
    lane verdict -> 403/proxy -> native pong, over real sockets.
    In this environment the chip sits behind a network tunnel, so the
    e2e figures are dominated by per-batch tunnel transfer/latency
    (see e2e_note); the kernel number is the chip-side capability.
  * dataplane_*: the same serving path with the DEVICE OUT of the loop
    (canned verdicts) — the data plane + ring transport capacity of
    this host, independent of chip or tunnel (see dataplane_note for
    the 1-cpu-host limit analysis).

Method: UNFILTERED 500-rule CRS-style ruleset (pingoo_tpu/utils/crs.py;
includes \\b and >31-position multi-word patterns — whatever the
compiler cannot lower is host-interpreted and reported via
`device_residency`) + 128k-entry IP blocklist + 4k ASN bitset;
replayed-log-style traffic at 5% attack rate. Timing uses a device-side
chained loop (each iteration's verdict feeds a carried checksum, and the
checksum salts EVERY input column of the next iteration, so XLA's
while-loop invariant code motion cannot hoist any of the verdict out of
the loop) with an empty-loop floor subtracted: per-call wall timing is
unreliable on tunneled devices, where dispatch returns before execution
completes. The
per-batch figure is therefore pure on-chip verdict time over the
device-resident rules; `p_batch_ms` is also the added verdict latency
for a full batch (the <2 ms budget).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np


def bench_blocklist_1m(iters: int = 50, batch: int = 8192) -> dict:
    """BASELINE config 3: 1M-entry IP/CIDR blocklist membership on HBM
    (reference lists.rs:48-125 loads these into a bel array the
    interpreter scans per request)."""
    import jax
    import jax.numpy as jnp

    from pingoo_tpu.ops.cidr import (
        build_cidr_table,
        index_v4_buckets,
        v4_buckets_contains,
    )

    rng = np.random.default_rng(20260729)
    addrs = np.unique(rng.integers(
        0x01000000, 0xDF000000, size=960_000, dtype=np.uint32))
    nets24 = np.unique(rng.integers(
        0x010000, 0xDF0000, size=70_000, dtype=np.uint32))
    n_entries = int(len(addrs) + len(nets24))
    nmax = max(len(addrs), len(nets24))
    keys = np.full((2, nmax), 0xFFFFFFFF, dtype=np.uint32)
    keys[0, : len(nets24)] = np.sort(nets24)
    keys[1, : len(addrs)] = np.sort(addrs)
    buckets = index_v4_buckets(
        keys,
        np.array([24, 32], dtype=np.int32),
        np.array([len(nets24), len(addrs)], dtype=np.int32),
        build_cidr_table([]),
    )

    # ~10% member probes, v6-mapped words.
    probes_v4 = rng.integers(0x01000000, 0xDF000000, size=batch,
                             dtype=np.uint32)
    members = rng.choice(addrs, size=batch // 10, replace=False)
    probes_v4[: len(members)] = members
    probes = np.zeros((batch, 4), dtype=np.uint32)
    probes[:, 2] = 0xFFFF
    probes[:, 3] = probes_v4
    ips = jax.device_put(probes)

    @jax.jit
    def run_n(buckets, ips, n):
        def body(i, acc):
            # Salt depends on the carried checksum (defeats dead-code
            # elimination) AND the loop index (alternates even if the
            # hit-count parity sticks, so inputs are never invariant).
            salted = ips.at[:, 3].set(
                ips[:, 3] + ((acc + i) % 2).astype(jnp.uint32))
            hit = v4_buckets_contains(buckets, salted)
            return acc + hit.sum().astype(jnp.int64)
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    @jax.jit
    def floor_loop(ips, n):
        def body(i, acc):
            return acc + ips[:, 3].sum().astype(jnp.int64) + i
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    int(run_n(buckets, ips, 2))
    int(floor_loop(ips, 2))
    t0 = time.time()
    int(floor_loop(ips, iters))
    floor = time.time() - t0
    t0 = time.time()
    checksum = int(run_n(buckets, ips, iters))
    full = time.time() - t0
    per_batch = max((full - floor) / iters, 1e-9)
    return {
        "blocklist_entries": n_entries,
        "blocklist_lookups_per_s": round(batch / per_batch, 1),
        "blocklist_checksum": checksum,
    }


def autotune_scan_strategies(plan, tables, arrays, iters: int = 30) -> dict:
    """Micro-autotune hook: measure the per-LOOP-ITERATION cost of each
    NFA scan strategy (lax.scan single/pair, fused Pallas single/pair)
    on the LIVE backend with the same chained-salted-loop method as the
    headline bench, on the widest bank (it dominates the verdict).
    Returns a DEFAULT_STEP_COSTS-shaped dict (relative to "scan") for
    compiler.plan.reselect_scan_strategies; {} when there is no bank."""
    import jax
    import jax.numpy as jnp

    from pingoo_tpu.ops.nfa_scan import (extract_slots, init_scan_state,
                                         scan_chunk)

    keys = [k for k in plan.scan_plans if k in tables]
    if not keys:
        return {}
    key = max(keys, key=lambda k: int(tables[k].opt.shape[0]))
    bank = tables[key]
    field = key[len("nfa_"):]
    data = arrays[f"{field}_bytes"]
    lens = arrays[f"{field}_len"]
    B, L = data.shape
    W = int(bank.opt.shape[0])
    variants = {
        "scan": (None, None),
        "pair": ("pair", None),
        "pallas": (None, "pallas"),
        "pallas_pair": ("pair", "pallas"),
    }
    raw = {}
    for name, (lookup, backend) in variants.items():
        loop_iters = (L + 1) // 2 if lookup == "pair" else L

        @jax.jit
        def run_n(data, lens, n, lookup=lookup, backend=backend):
            def body(i, acc):
                # salt from the carried checksum + loop index: no
                # loop-invariant inputs for XLA to hoist (see the
                # headline bench's measurement notes).
                salted = data ^ ((acc + i) % 2).astype(jnp.uint8)
                state = scan_chunk(bank, salted, lens,
                                   init_scan_state(B, W), 0,
                                   lookup=lookup, backend=backend)
                hits = extract_slots(bank, state, lens)
                return acc + hits.sum().astype(jnp.int64)

            return jax.lax.fori_loop(0, n, body, jnp.int64(0))

        @jax.jit
        def floor_loop(data, n):
            def body(i, acc):
                return acc + data.sum().astype(jnp.int64) + i

            return jax.lax.fori_loop(0, n, body, jnp.int64(0))

        try:
            int(run_n(data, lens, 2))
            int(floor_loop(data, 2))
            t0 = time.time()
            int(floor_loop(data, iters))
            floor = time.time() - t0
            t0 = time.time()
            int(run_n(data, lens, iters))
            full = time.time() - t0
        except Exception:
            continue  # a strategy that fails to compile is never selected
        raw[name] = max(full - floor, 1e-9) / iters / loop_iters
    base = raw.get("scan")
    if not base:
        return {}
    return {k: v / base for k, v in raw.items()}


def bench_prefilter_modes(plan, tables, arrays, verdict_body,
                          iters: int = 30) -> dict:
    """ISSUE 4: per-mode verdict throughput for the literal-prefilter
    cascade (PINGOO_PREFILTER=off|banks|compact) with the same
    chained-salted-loop method as the headline bench, plus the Stage-A
    candidate statistics (rate, banks skipped) on the bench traffic.
    Selects the fastest mode into plan.prefilter.default_mode (persisted
    by the caller via the artifact cache) and writes the
    BENCH_prefilter.json trajectory artifact."""
    import jax
    import jax.numpy as jnp

    out: dict = {"modes": {}}
    batch = int(arrays["asn"].shape[0])
    prev = os.environ.get("PINGOO_PREFILTER")
    try:
        for mode in ("off", "banks", "compact"):
            os.environ["PINGOO_PREFILTER"] = mode

            # Fresh jit per mode: the mode is read at trace time.
            @jax.jit
            def run_n(tables, arrays, n):
                def body(i, acc):
                    m = verdict_body(tables, arrays, (acc + i) % 2)
                    return acc + m.sum().astype(jnp.int64)
                return jax.lax.fori_loop(0, n, body, jnp.int64(0))

            @jax.jit
            def floor_loop(arrays, n):
                def body(i, acc):
                    return acc + arrays["asn"].sum() + i
                return jax.lax.fori_loop(0, n, body, jnp.int64(0))

            try:
                t0 = time.time()
                checksum = int(run_n(tables, arrays, 2))
                int(floor_loop(arrays, 2))
                compile_s = time.time() - t0
                t0 = time.time()
                int(floor_loop(arrays, iters))
                floor = time.time() - t0
                t0 = time.time()
                checksum = int(run_n(tables, arrays, iters))
                full = time.time() - t0
            except Exception as exc:
                out["modes"][mode] = {"error": repr(exc)[:200]}
                continue
            per_batch_s = max((full - floor) / iters, 1e-9)
            out["modes"][mode] = {
                "req_per_s": round(batch / per_batch_s, 1),
                "p_batch_ms": round(per_batch_s * 1000, 3),
                "compile_s": round(compile_s, 1),
                "checksum": checksum,
            }
    finally:
        if prev is None:
            os.environ.pop("PINGOO_PREFILTER", None)
        else:
            os.environ["PINGOO_PREFILTER"] = prev

    # Stage-A candidate statistics on the (unsalted) bench traffic.
    try:
        from pingoo_tpu.engine.verdict import make_prefilter_fn

        os.environ["PINGOO_PREFILTER"] = "banks"
        try:
            pf = make_prefilter_fn(plan)
        finally:
            if prev is None:
                os.environ.pop("PINGOO_PREFILTER", None)
            else:
                os.environ["PINGOO_PREFILTER"] = prev
        if pf is not None:
            pf_fn, n_gated = pf.fn, len(pf.gated)
            _, aux = pf_fn(tables, arrays)
            aux = np.asarray(aux)
            out["banks_gated"] = n_gated
            out["banks_skipped_per_batch"] = int(aux[1])
            out["candidate_rate"] = (
                round(int(aux[0]) / (batch * n_gated), 4) if n_gated
                else 0.0)
        pfp = getattr(plan, "prefilter", None)
        if pfp is not None:
            out["factors"] = {f: ff.num_factors
                              for f, ff in pfp.fields.items()}
    except Exception as exc:
        out["stats_error"] = repr(exc)[:200]

    base = out["modes"].get("off", {}).get("req_per_s")
    best_mode, best_rps = "off", base or 0
    for mode, row in out["modes"].items():
        rps = row.get("req_per_s")
        if base:
            row["speedup_vs_off"] = round(rps / base, 3) if rps else None
        if rps and rps > best_rps:
            best_mode, best_rps = mode, rps
    out["selected"] = best_mode
    if getattr(plan, "prefilter", None) is not None:
        plan.prefilter.default_mode = best_mode

    try:
        with open("BENCH_prefilter.json", "w") as f:
            json.dump({
                "metric": "prefilter_cascade_modes",
                "batch_size": batch,
                **out,
            }, f, indent=2)
    except OSError:
        pass
    return out


def bench_dfa_modes(plan, tables, arrays, verdict_body,
                    iters: int = 30) -> dict:
    """ISSUE 8: per-mode verdict throughput for the bitsplit-DFA
    lowering (PINGOO_DFA=off|auto|force) with the same chained-salted-
    loop method as the headline bench, plus the per-bank lowering
    summary (state counts, exact vs approximate). Selects the fastest
    mode into plan.dfa_default_mode (persisted by the caller via the
    artifact cache) and writes the BENCH_dfa.json trajectory artifact.
    The off mode is the PR 4 compact-cascade baseline, so
    speedup_vs_off is the ISSUE 8 acceptance number."""
    import jax
    import jax.numpy as jnp

    out: dict = {"modes": {}}
    batch = int(arrays["asn"].shape[0])
    prev = os.environ.get("PINGOO_DFA")
    try:
        for mode in ("off", "auto", "force"):
            os.environ["PINGOO_DFA"] = mode

            # Fresh jit per mode: the mode is read at trace time.
            @jax.jit
            def run_n(tables, arrays, n):
                def body(i, acc):
                    m = verdict_body(tables, arrays, (acc + i) % 2)
                    return acc + m.sum().astype(jnp.int64)
                return jax.lax.fori_loop(0, n, body, jnp.int64(0))

            @jax.jit
            def floor_loop(arrays, n):
                def body(i, acc):
                    return acc + arrays["asn"].sum() + i
                return jax.lax.fori_loop(0, n, body, jnp.int64(0))

            try:
                t0 = time.time()
                checksum = int(run_n(tables, arrays, 2))
                int(floor_loop(arrays, 2))
                compile_s = time.time() - t0
                t0 = time.time()
                int(floor_loop(arrays, iters))
                floor = time.time() - t0
                t0 = time.time()
                checksum = int(run_n(tables, arrays, iters))
                full = time.time() - t0
            except Exception as exc:
                out["modes"][mode] = {"error": repr(exc)[:200]}
                continue
            per_batch_s = max((full - floor) / iters, 1e-9)
            out["modes"][mode] = {
                "req_per_s": round(batch / per_batch_s, 1),
                "p_batch_ms": round(per_batch_s * 1000, 3),
                "compile_s": round(compile_s, 1),
                "checksum": checksum,
            }
    finally:
        if prev is None:
            os.environ.pop("PINGOO_DFA", None)
        else:
            os.environ["PINGOO_DFA"] = prev

    # Per-bank lowering summary (host-static, from the plan).
    try:
        banks = {}
        for key, e in plan.scan_plans.items():
            if not e.dfa_key or e.dfa_key not in plan.np_tables:
                continue
            dtab = plan.np_tables[e.dfa_key]
            banks[key] = {
                "states": int(dtab.num_states),
                "classes": int(dtab.num_classes),
                "exact": bool(dtab.exact),
                "auto": bool(e.dfa_auto),
            }
        for key, dkey in getattr(plan, "win_dfa", {}).items():
            if dkey not in plan.np_tables:
                continue
            dtab = plan.np_tables[dkey]
            banks[key] = {
                "states": int(dtab.num_states),
                "classes": int(dtab.num_classes),
                "exact": bool(dtab.exact),
                # Window DFAs dispatch on the row-work-bound CPU
                # backend under auto (engine/verdict._dfa_win_active).
                "auto": "cpu-only",
            }
        out["banks"] = banks
    except Exception as exc:
        out["stats_error"] = repr(exc)[:200]

    base = out["modes"].get("off", {}).get("req_per_s")
    best_mode, best_rps = "off", base or 0
    for mode, row in out["modes"].items():
        rps = row.get("req_per_s")
        if base:
            row["speedup_vs_off"] = round(rps / base, 3) if rps else None
        if rps and rps > best_rps:
            best_mode, best_rps = mode, rps
    out["selected"] = best_mode
    plan.dfa_default_mode = best_mode

    try:
        with open("BENCH_dfa.json", "w") as f:
            json.dump({
                "metric": "bitsplit_dfa_modes",
                "batch_size": batch,
                **out,
            }, f, indent=2)
    except OSError:
        pass
    return out


def _mesh_arg() -> str | None:
    """`--mesh dpxtpxsp` (or BENCH_MESH) selects the serving-mesh shape
    the scheduler bench runs under; None disables the bench unless
    BENCH_SCHED=1 asks for the 1x1x1 scheduler A/B alone."""
    if "--mesh" in sys.argv:
        i = sys.argv.index("--mesh")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return os.environ.get("BENCH_MESH") or None


def bench_sched(mesh_spec: str) -> dict:
    """ISSUE 6 satellite: measure the admission SCHEDULER modes
    (fixed-window vs continuous, docs/SCHEDULER.md) and the serving
    mesh by driving a bursty request stream through a live
    VerdictService. Runs in a SUBPROCESS so the dp*tp*sp virtual CPU
    devices can be forced before jax initializes (the same shape
    `make mesh-smoke` and tests/test_mesh_serving.py use); the parent
    process keeps its own backend untouched. Returns flattened
    `sched_*` keys for the result line — tools/bench_regress.py tracks
    continuous throughput, p99, slack, and the deadline-miss rate."""
    dims = [int(x) for x in mesh_spec.lower().split("x")]
    if len(dims) != 3 or any(d < 1 for d in dims):
        raise ValueError(f"bad --mesh spec {mesh_spec!r}")
    ndev = dims[0] * dims[1] * dims[2]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
    flags.append(f"--xla_force_host_platform_device_count={max(ndev, 2)}")
    env["XLA_FLAGS"] = " ".join(flags)
    env["PINGOO_MESH"] = mesh_spec
    repo = os.path.dirname(os.path.abspath(__file__))
    out = _run_tracked(
        [sys.executable, "-c", "import bench; bench._sched_bench_child()"],
        capture_output=True, text=True, timeout=1200, env=env, cwd=repo)
    if out.returncode != 0:
        raise RuntimeError(
            f"sched bench child rc={out.returncode}: "
            f"{(out.stderr or '')[-300:]}")
    child = json.loads(out.stdout.strip().splitlines()[-1])
    res = {"sched_mesh": mesh_spec, "sched_mesh_devices": ndev,
           "sched_deadline_ms": child.get("deadline_ms"),
           "sched_batch": child.get("max_batch")}
    for mode, row in child.get("modes", {}).items():
        for key, val in row.items():
            res[f"sched_{mode}_{key}"] = val
    cont = child.get("modes", {}).get("continuous", {})
    # The regress-tracked aliases (direction-aware, bench_regress.py).
    if "req_per_s" in cont:
        res["sched_continuous_req_per_s"] = cont["req_per_s"]
        res["sched_continuous_p99_ms"] = cont.get("p99_wait_ms")
        res["sched_deadline_miss_rate"] = cont.get("deadline_miss_rate")
        res["sched_p99_slack_ms"] = cont.get("p99_slack_ms")
    return res


def _sched_bench_child() -> None:
    """Child body of bench_sched (forced-device-count subprocess): boot
    VerdictService per scheduler mode, serve a bursty replayed-traffic
    stream, emit one JSON line with per-mode throughput/latency/miss
    statistics. Per-request latency is measured around evaluate() in
    the driver (the registry's wait histogram is process-global and
    would mix the two modes)."""
    import asyncio
    import time as _time

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine.service import VerdictService
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    n_rules = int(os.environ.get("BENCH_SCHED_RULES", "60"))
    n_reqs = int(os.environ.get("BENCH_SCHED_REQUESTS", "1024"))
    burst = int(os.environ.get("BENCH_SCHED_BURST", "64"))
    max_batch = int(os.environ.get("BENCH_SCHED_BATCH", "256"))
    rules, lists = generate_ruleset(n_rules, with_lists=True,
                                    list_sizes=(4096, 512))
    plan = compile_ruleset(rules, lists)
    reqs = generate_traffic(n_reqs, lists=lists, seed=7)
    result: dict = {"modes": {}, "max_batch": max_batch,
                    "rules": n_rules, "requests": n_reqs}

    for mode in ("fixed", "continuous"):
        os.environ["PINGOO_SCHED_MODE"] = mode
        svc = VerdictService(plan, lists, use_device=True,
                             max_batch=max_batch, max_wait_us=300)
        result["deadline_ms"] = svc.sched.config.deadline_ms
        waits: list[float] = []

        async def timed(svc=svc, waits=waits, r=None):
            t0 = _time.monotonic()
            v = await svc.evaluate(r)
            waits.append((_time.monotonic() - t0) * 1e3)
            return v

        async def drive(svc=svc, waits=waits):
            await svc.start()
            # Warm the per-bucket XLA programs off the measured run (a
            # first-burst compile would otherwise own the p99).
            await asyncio.gather(*[svc.evaluate(r)
                                   for r in reqs[:burst]])
            miss0 = svc.sched.deadline_misses
            launch0 = svc.sched.launches
            t0 = _time.monotonic()
            for i in range(0, n_reqs, burst):
                await asyncio.gather(*[
                    timed(svc, waits, r) for r in reqs[i:i + burst]])
            elapsed = _time.monotonic() - t0
            await svc.stop()
            return elapsed, miss0, launch0

        elapsed, miss0, launch0 = asyncio.run(drive())
        waits.sort()
        p99 = waits[min(len(waits) - 1, int(0.99 * len(waits)))]
        deadline_ms = svc.sched.config.deadline_ms
        launches = svc.sched.launches - launch0
        result["modes"][mode] = {
            "req_per_s": round(n_reqs / elapsed, 1),
            "p50_wait_ms": round(waits[len(waits) // 2], 3),
            "p99_wait_ms": round(p99, 3),
            "p99_slack_ms": round(deadline_ms - p99, 3),
            "deadline_miss_rate": round(
                (svc.sched.deadline_misses - miss0) / n_reqs, 4),
            "launches": launches,
            "mean_launch_occupancy": round(
                n_reqs / launches, 1) if launches else 0.0,
        }
    print(json.dumps(result), flush=True)


def bench_body() -> dict:
    """ISSUE 13 satellite: throughput of the streaming body scanner
    (engine/bodyscan.py) over interleaved multi-flow window streams —
    the shape the ring sidecar actually drains — A/B'd against the
    contiguous one-shot scan of the same payloads. Verdict equality
    across both framings and the interpreter oracle is enforced:
    streaming is a framing change, never a semantic one. Writes
    BENCH_body.json; tools/bench_regress.py tracks the streamed
    throughput."""
    import random as _random

    from pingoo_tpu.engine import bodyscan

    n_flows = int(os.environ.get("BENCH_BODY_FLOWS", "192"))
    plan = bodyscan.compile_body_plan()
    window = bodyscan.body_window_bytes()
    rng = _random.Random(1306)
    # Filler alphabet free of rule-literal bytes (space, quotes, <, .,
    # /, parens) so only the planted literals can match.
    alpha = b"abcdefghijklmnop0123456789=&"
    lits = [r.pattern.encode() for r in bodyscan.DEFAULT_BODY_RULES]
    payloads = []
    for i in range(n_flows):
        body = bytes(rng.choices(alpha, k=rng.randint(256, 3 * window)))
        if i % 3 == 0:  # a third carry a literal at a random offset
            lit = lits[i % len(lits)]
            at = rng.randint(0, len(body))
            body = body[:at] + lit + body[at:]
        payloads.append(body)
    total_bytes = sum(map(len, payloads))

    def make_windows():
        """Round-robin interleave the flows' windows, the arrival
        order a busy listener actually produces."""
        per_flow = []
        for fid, payload in enumerate(payloads):
            parts = bodyscan.split_payload(payload, window)
            per_flow.append([bodyscan.BodyWindow(
                flow_id=fid, win_seq=s, data=d,
                final=(s == len(parts) - 1))
                for s, d in enumerate(parts)])
        rounds, depth = [], max(map(len, per_flow))
        for r in range(depth):
            rounds.append([w[r] for w in per_flow if len(w) > r])
        return rounds

    def stream_pass():
        scanner = bodyscan.BodyScanner(plan)
        out = {}
        for batch in make_windows():
            for v in scanner.scan_windows(batch):
                out[v.flow_id] = v
        return out

    stream_pass()  # warm the chunk kernels off the clock
    t0 = time.time()
    streamed = stream_pass()
    stream_s = time.time() - t0

    scanner = bodyscan.BodyScanner(plan)
    t0 = time.time()
    contig = {fid: scanner.scan_buffered(p)
              for fid, p in enumerate(payloads)}
    contig_s = time.time() - t0

    mismatches = 0
    for fid, payload in enumerate(payloads):
        unv, vb, _ = bodyscan.body_lanes_oracle(plan, payload)
        sv, cv = streamed.get(fid), contig[fid]
        if (sv is None or sv.degraded or cv.degraded
                or sv.unverified != unv or cv.unverified != unv
                or sv.verified_block != vb or cv.verified_block != vb):
            mismatches += 1
    child = {
        "flows": n_flows,
        "bytes_total": total_bytes,
        "window_bytes": window,
        "body_stream_mb_per_s": round(total_bytes / stream_s / 1e6, 2),
        "body_contig_mb_per_s": round(total_bytes / contig_s / 1e6, 2),
        "body_verdict_mismatches": mismatches,
    }
    if contig_s > 0 and stream_s > 0:
        child["stream_vs_contig"] = round(contig_s / stream_s, 3)
    try:
        with open("BENCH_body.json", "w") as f:
            json.dump({"metric": "body_streaming_scan", **child},
                      f, indent=2)
    except OSError:
        pass
    if mismatches:
        raise RuntimeError(
            f"body bench: {mismatches} verdict mismatch(es) between "
            f"streamed / contiguous / oracle")
    return child


def bench_pipeline() -> dict:
    """ISSUE 9 satellite: A/B the zero-copy pipelined executor
    (PINGOO_PIPELINE=off vs on, docs/EXECUTOR.md) by driving the same
    seeded traffic stream through a live ring + RingSidecar per mode in
    a SUBPROCESS (fresh jit caches per run; the parent backend stays
    untouched), plus a third `mega` arm (ISSUE 12: PINGOO_PIPELINE=on
    + PINGOO_MEGASTEP=force) that amortizes one dispatch over K batch
    slices. Verdict checksums must be identical across all modes — the
    pipeline and the megastep are scheduling changes, never semantic
    ones. Writes BENCH_pipeline.json and returns flattened
    `pipeline_*`/`megastep_*` keys for the result line;
    tools/bench_regress.py tracks on-mode and megastep throughput and
    p99."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = _run_tracked(
        [sys.executable, "-c", "import bench; bench._pipeline_bench_child()"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=repo)
    if out.returncode != 0:
        raise RuntimeError(
            f"pipeline bench child rc={out.returncode}: "
            f"{(out.stderr or '')[-300:]}")
    child = json.loads(out.stdout.strip().splitlines()[-1])
    if "note" in child:
        return {"pipeline_note": child["note"]}
    on = child["modes"].get("on", {})
    off = child["modes"].get("off", {})
    mega = child["modes"].get("mega", {})
    child["checksum_match"] = (on.get("checksum") == off.get("checksum")
                               and on.get("checksum") is not None)
    if off.get("req_per_s") and on.get("req_per_s"):
        child["speedup"] = round(on["req_per_s"] / off["req_per_s"], 3)
    # ISSUE 12 acceptance surface: the megastep arm must be checksum-
    # identical to the per-batch oracle, and its win over the pipelined
    # per-batch arm is the dispatch-amortization headline.
    child["megastep_checksum_match"] = (
        mega.get("checksum") == off.get("checksum")
        and mega.get("checksum") is not None)
    if mega.get("req_per_s") and on.get("req_per_s"):
        child["megastep_speedup_vs_on"] = round(
            mega["req_per_s"] / on["req_per_s"], 3)
    try:
        with open("BENCH_pipeline.json", "w") as f:
            json.dump({"metric": "pipelined_executor_modes", **child},
                      f, indent=2)
    except OSError:
        pass
    res = {"pipeline_checksum_match": child["checksum_match"],
           "pipeline_speedup": child.get("speedup")}
    for mode, row in child["modes"].items():
        for key, val in row.items():
            if key != "checksum":
                res[f"pipeline_{mode}_{key}"] = val
    # The regress-tracked aliases (direction-aware, bench_regress.py).
    res["pipeline_on_req_per_s"] = on.get("req_per_s")
    res["pipeline_on_p99_ms"] = on.get("p99_wait_ms")
    res["pipeline_overlap_ratio"] = on.get("overlap_ratio")
    res["megastep_req_per_s"] = mega.get("req_per_s")
    res["megastep_checksum_match"] = child["megastep_checksum_match"]
    res["megastep_speedup_vs_on"] = child.get("megastep_speedup_vs_on")
    return res


def _pipeline_bench_child() -> None:
    """Child body of bench_pipeline: per PINGOO_PIPELINE mode, boot a
    fresh shm ring + RingSidecar, drive the same seeded traffic with
    INTERLEAVED verdict polling (both rings are finite — a driver that
    enqueues the whole stream before polling wedges against the
    sidecar's full-verdict-ring retry loop), and emit one JSON line
    with per-mode throughput / p99 / verdict checksum plus the on-mode
    overlap telemetry."""
    import socket as _socket
    import tempfile
    import time as _time
    import zlib

    from pingoo_tpu import native_ring
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.native_ring import Ring, RingSidecar
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    if not native_ring.ensure_built():
        print(json.dumps({"note": "native toolchain unavailable"}),
              flush=True)
        return
    n_rules = int(os.environ.get("BENCH_PIPELINE_RULES", "500"))
    # 8 full batches at the default B=2048: with only 4 the A/B delta
    # sits below the GIL/scheduler jitter floor on shared CPU hosts.
    n_reqs = int(os.environ.get("BENCH_PIPELINE_REQUESTS", "16384"))
    max_batch = int(os.environ.get("BENCH_PIPELINE_BATCH", "2048"))
    depth = int(os.environ.get("BENCH_PIPELINE_DEPTH", "3"))
    rules, lists = generate_ruleset(n_rules, with_lists=True,
                                    list_sizes=(4096, 512))
    plan = compile_ruleset(rules, lists)

    def _pack(reqs):
        packed = []
        for r in reqs:
            try:
                ip = (b"\x00" * 10 + b"\xff\xff"
                      + _socket.inet_aton(r.ip))  # v6-mapped, ABI order
            except OSError:
                ip = b"\x00" * 16
            packed.append((r.method.encode(), r.host.encode(),
                           r.path.encode(), r.url.encode(),
                           r.user_agent.encode(), ip, r.remote_port,
                           r.asn, r.country.encode()))
        return packed

    # Warm with the SAME request count as the measured drive: batch
    # shapes form from whatever backlog the sidecar sees at dequeue
    # time, so a short warm stream leaves pow2 buckets uncompiled and
    # a multi-second jit compile lands inside the measured window —
    # which is an arm-order lottery, not an executor comparison.
    warm = _pack(generate_traffic(n_reqs, lists=lists, seed=12))
    traffic = _pack(generate_traffic(n_reqs, lists=lists, seed=11))
    result: dict = {"modes": {}, "max_batch": max_batch,
                    "rules": n_rules, "requests": n_reqs, "depth": depth}

    def drive(ring, stream, record=None):
        """Enqueue `stream` with interleaved polling; -> wall seconds.
        `record` (ticket -> stream index map + per-request waits)
        collects checksum/latency inputs for the measured run."""
        t_enq: dict[int, float] = {}
        idx_of: dict[int, int] = {}
        actions: dict[int, int] = {}
        waits: list[float] = []
        done = 0
        i = 0
        t0 = _time.monotonic()
        while done < len(stream):
            # Burst-enqueue up to a batch per poll round: one request
            # per iteration drip-feeds the ring, so the sidecar's
            # dequeue pass drains it dry and every arm serves
            # artificial near-empty backlogs instead of the deep-queue
            # regime the executor (and megastep windows) batch against.
            burst = 0
            while i < len(stream) and burst < 64:
                m, h, p, u, ua, ip, port, asn, cc = stream[i]
                t = ring.enqueue(method=m, host=h, path=p, url=u,
                                 user_agent=ua, ip=ip, port=port,
                                 asn=asn, country=cc)
                if t is None:
                    break
                idx_of[t] = i
                t_enq[t] = _time.monotonic()
                i += 1
                burst += 1
            v = ring.poll_verdict()
            while v is not None:
                ticket, action, _score = v
                now = _time.monotonic()
                waits.append((now - t_enq.pop(ticket, now)) * 1e3)
                actions[idx_of.pop(ticket, -1)] = action
                done += 1
                v = ring.poll_verdict()
        elapsed = _time.monotonic() - t0
        if record is not None:
            record["waits"] = waits
            record["checksum"] = zlib.crc32(
                bytes(actions[j] for j in sorted(actions)))
        return elapsed

    # Third arm (ISSUE 12): pipelining on PLUS the device-resident
    # megastep — one dispatch amortized over K batch slices. Same
    # stream, so the checksum must match `off` bit-for-bit.
    for mode in ("off", "on", "mega"):
        os.environ["PINGOO_PIPELINE"] = "on" if mode == "mega" else mode
        if mode == "mega":
            os.environ["PINGOO_MEGASTEP"] = "force"
            os.environ["PINGOO_MEGASTEP_K"] = os.environ.get(
                "BENCH_MEGASTEP_K", "4")
        else:
            os.environ.pop("PINGOO_MEGASTEP", None)
            os.environ.pop("PINGOO_MEGASTEP_K", None)
        tmp = tempfile.mkdtemp(prefix="pingoo-pipe-bench-")
        # Capacity must hold a full megastep window's worth of backlog
        # (K x max_batch) or K-deep windows can never fill from real
        # queue pressure — 4096 capped the mega arm at 2 slices of
        # B=2048. 16384 matches the e2e/dataplane benches; same for
        # all three arms.
        ring = Ring(os.path.join(tmp, "ring"), capacity=16384,
                    create=True)
        sidecar = RingSidecar(ring, plan, lists, max_batch=max_batch,
                              pipeline_depth=depth)
        th = threading.Thread(target=sidecar.run, daemon=True)
        th.start()
        drive(ring, warm)  # compile the hot pow2 buckets off the clock
        # Best-of-2 measured drives: the stream is identical, so the
        # checksum is too, and the faster wall isolates executor
        # behavior from scheduler-jitter outliers on shared CPU.
        rec: dict = {}
        elapsed = drive(ring, traffic, record=rec)
        rec2: dict = {}
        elapsed2 = drive(ring, traffic, record=rec2)
        if elapsed2 < elapsed:
            elapsed, rec = elapsed2, rec2
        snap = sidecar.stats().get("pipeline", {})
        cost = sidecar.sched.cost.snapshot()
        sidecar.stop()
        ring.close()
        waits = sorted(rec["waits"])
        row = {
            "req_per_s": round(n_reqs / elapsed, 1),
            "p50_wait_ms": round(waits[len(waits) // 2], 3),
            "p99_wait_ms": round(
                waits[min(len(waits) - 1, int(0.99 * len(waits)))], 3),
            "checksum": rec["checksum"],
            "overlap_ratio": snap.get("overlap_ratio"),
            "overlap_events": snap.get("overlap_events"),
            "stage_occupancy": snap.get("stage_occupancy"),
        }
        if mode == "on":
            row["stage_ewma_ms"] = cost.get("stage_ewma_ms")
        if mode == "mega":
            row["megastep"] = snap.get("megastep")
            row["megastep_ewma_ms"] = cost.get("megastep_ewma_ms")
        result["modes"][mode] = row
    print(json.dumps(result), flush=True)


def bench_staging() -> dict:
    """ISSUE 15 satellite: A/B compact staging (PINGOO_STAGING=full vs
    compact, docs/EXECUTOR.md) by driving the same seeded traffic —
    with a long-URL tail, the regime that makes full-mode per-batch
    width bucketing balloon to the field spec — through a live ring +
    RingSidecar per mode in a SUBPROCESS. Both arms run under the
    PINGOO_STAGING_DEPTH=256 operator clamp (a no-op for `full`, which
    ignores caps); verdict checksums must be identical — compact
    staging is a transport change, never a semantic one (depth-overflow
    rows re-serve from full slot bytes). Writes BENCH_staging.json;
    tools/bench_regress.py tracks compact throughput (higher-better)
    and staged bytes/request (lower-better)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = _run_tracked(
        [sys.executable, "-c", "import bench; bench._staging_bench_child()"],
        capture_output=True, text=True, timeout=1800, env=env, cwd=repo)
    if out.returncode != 0:
        raise RuntimeError(
            f"staging bench child rc={out.returncode}: "
            f"{(out.stderr or '')[-300:]}")
    child = json.loads(out.stdout.strip().splitlines()[-1])
    if "note" in child:
        return {"staging_note": child["note"]}
    full = child["modes"].get("full", {})
    compact = child["modes"].get("compact", {})
    child["checksum_match"] = (
        full.get("checksum") == compact.get("checksum")
        and full.get("checksum") is not None)
    if full.get("staged_bytes_per_req") and compact.get(
            "staged_bytes_per_req"):
        child["bytes_reduction"] = round(
            full["staged_bytes_per_req"] / compact["staged_bytes_per_req"],
            2)
    if full.get("req_per_s") and compact.get("req_per_s"):
        child["speedup"] = round(
            compact["req_per_s"] / full["req_per_s"], 3)
    try:
        with open("BENCH_staging.json", "w") as f:
            json.dump({"metric": "compact_staging_modes", **child},
                      f, indent=2)
    except OSError:
        pass
    if not child["checksum_match"]:
        raise RuntimeError(
            f"staging checksum mismatch: full={full.get('checksum')} "
            f"compact={compact.get('checksum')}")
    res = {"staging_checksum_match": child["checksum_match"],
           "staging_speedup": child.get("speedup"),
           "staging_bytes_reduction": child.get("bytes_reduction")}
    for mode, row in child["modes"].items():
        for key, val in row.items():
            if key != "checksum":
                res[f"staging_{mode}_{key}"] = val
    # The regress-tracked aliases (direction-aware, bench_regress.py).
    res["staging_compact_req_per_s"] = compact.get("req_per_s")
    res["staged_bytes_per_req"] = compact.get("staged_bytes_per_req")
    return res


def _staging_bench_child() -> None:
    """Child body of bench_staging: per PINGOO_STAGING mode, boot a
    fresh shm ring + RingSidecar, drive the same seeded long-URL-tail
    traffic with interleaved polling, and emit one JSON line with
    per-mode throughput / p99 / staged bytes per request / dispatch
    EWMA / verdict checksum."""
    import dataclasses
    import socket as _socket
    import tempfile
    import time as _time
    import zlib

    from pingoo_tpu import native_ring
    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.native_ring import Ring, RingSidecar
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    if not native_ring.ensure_built():
        print(json.dumps({"note": "native toolchain unavailable"}),
              flush=True)
        return
    n_rules = int(os.environ.get("BENCH_STAGING_RULES", "500"))
    n_reqs = int(os.environ.get("BENCH_STAGING_REQUESTS", "8192"))
    max_batch = int(os.environ.get("BENCH_STAGING_BATCH", "2048"))
    depth = int(os.environ.get("BENCH_STAGING_PIPE_DEPTH", "3"))
    # Both arms share the operator clamp: `full` ignores caps entirely
    # (the bit-exact oracle), `compact` caps url/path at 256 and
    # re-serves the rare deeper-dependent row from full slot bytes.
    os.environ.setdefault("PINGOO_STAGING_DEPTH", "256")
    rules, lists = generate_ruleset(n_rules, with_lists=True,
                                    list_sizes=(4096, 512))
    plan = compile_ruleset(rules, lists)

    def _tail(reqs, rng_seed):
        """Give ~0.5% of the stream near-spec-width url/path values:
        the long-tail shape (search queries, encoded payloads) under
        which full-mode content bucketing stages the whole batch at
        the 2048 field spec while compact stays at the clamped cap."""
        import random as _random
        rng = _random.Random(rng_seed)
        out = list(reqs)
        for i in range(0, len(out), 200):
            j = min(len(out) - 1, i + rng.randrange(200))
            r = out[j]
            pad = "".join(rng.choice("abcdefgh") for _ in range(1800))
            out[j] = dataclasses.replace(
                r, url=(r.path + "?q=" + pad)[:2040],
                path=(r.path + "/" + pad)[:2040])
        return out

    def _pack(reqs):
        packed = []
        for r in reqs:
            try:
                ip = (b"\x00" * 10 + b"\xff\xff"
                      + _socket.inet_aton(r.ip))  # v6-mapped, ABI order
            except OSError:
                ip = b"\x00" * 16
            packed.append((r.method.encode(), r.host.encode(),
                           r.path.encode(), r.url.encode(),
                           r.user_agent.encode(), ip, r.remote_port,
                           r.asn, r.country.encode()))
        return packed

    warm = _pack(_tail(generate_traffic(n_reqs, lists=lists, seed=22), 2))
    traffic = _pack(_tail(generate_traffic(n_reqs, lists=lists, seed=21), 1))
    result: dict = {"modes": {}, "max_batch": max_batch, "rules": n_rules,
                    "requests": n_reqs,
                    "staging_depth": os.environ["PINGOO_STAGING_DEPTH"]}

    def drive(ring, stream, record=None):
        t_enq: dict[int, float] = {}
        idx_of: dict[int, int] = {}
        actions: dict[int, int] = {}
        waits: list[float] = []
        done = 0
        i = 0
        t0 = _time.monotonic()
        while done < len(stream):
            burst = 0
            while i < len(stream) and burst < 64:
                m, h, p, u, ua, ip, port, asn, cc = stream[i]
                t = ring.enqueue(method=m, host=h, path=p, url=u,
                                 user_agent=ua, ip=ip, port=port,
                                 asn=asn, country=cc)
                if t is None:
                    break
                idx_of[t] = i
                t_enq[t] = _time.monotonic()
                i += 1
                burst += 1
            v = ring.poll_verdict()
            while v is not None:
                ticket, action, _score = v
                now = _time.monotonic()
                waits.append((now - t_enq.pop(ticket, now)) * 1e3)
                actions[idx_of.pop(ticket, -1)] = action
                done += 1
                v = ring.poll_verdict()
        elapsed = _time.monotonic() - t0
        if record is not None:
            record["waits"] = waits
            record["checksum"] = zlib.crc32(
                bytes(actions[j] & 0xFF for j in sorted(actions)))
        return elapsed

    for mode in ("full", "compact"):
        os.environ["PINGOO_STAGING"] = mode
        tmp = tempfile.mkdtemp(prefix="pingoo-staging-bench-")
        ring = Ring(os.path.join(tmp, "ring"), capacity=16384,
                    create=True)
        sidecar = RingSidecar(ring, plan, lists, max_batch=max_batch,
                              pipeline_depth=depth)
        th = threading.Thread(target=sidecar.run, daemon=True)
        th.start()
        drive(ring, warm)  # compile the hot shapes off the clock
        counter = sidecar._staged_bytes_counter[mode]
        bytes0 = float(counter._value)
        rec: dict = {}
        elapsed = drive(ring, traffic, record=rec)
        rec2: dict = {}
        elapsed2 = drive(ring, traffic, record=rec2)
        staged = float(counter._value) - bytes0
        if elapsed2 < elapsed:
            elapsed, rec = elapsed2, rec2
        cost = sidecar.sched.cost.snapshot()
        overflow_rows = sidecar.depth_overflow_rows
        sidecar.stop()
        ring.close()
        waits = sorted(rec["waits"])
        result["modes"][mode] = {
            "req_per_s": round(n_reqs / elapsed, 1),
            "p50_wait_ms": round(waits[len(waits) // 2], 3),
            "p99_wait_ms": round(
                waits[min(len(waits) - 1, int(0.99 * len(waits)))], 3),
            "checksum": rec["checksum"],
            "staged_bytes_per_req": round(staged / (2 * n_reqs), 1),
            "dispatch_ewma_ms": (cost.get("stage_ewma_ms") or {}).get(
                "dispatch"),
            "dispatch_bytes_ewma_ms": cost.get("dispatch_bytes_ewma_ms"),
            "depth_overflow_rows": overflow_rows,
        }
    print(json.dumps(result), flush=True)


def bench_e2e(plan, lists, n_requests: int = 100_000) -> dict:
    """Committed end-to-end drive: loadgen_http -> httpd -> ring ->
    sidecar (device lane verdict) -> 403 / proxy -> pong."""
    import tempfile

    from pingoo_tpu import native_ring
    from pingoo_tpu.native_ring import Ring, RingSidecar

    if not native_ring.ensure_built():
        return {"e2e_note": "native toolchain unavailable"}
    ndir = native_ring.NATIVE_DIR
    _run_tracked(["make", "-C", ndir, "httpd", "pong", "loadgen_http"],
                 check=True, capture_output=True)

    tmp = tempfile.mkdtemp(prefix="pingoo-bench-")
    ring_path = os.path.join(tmp, "ring")
    ring = Ring(ring_path, capacity=16384, create=True)
    sidecar = RingSidecar(ring, plan, lists, max_batch=1024,
                          pipeline_depth=3)
    threading.Thread(target=sidecar.run, daemon=True).start()
    pong = subprocess.Popen([os.path.join(ndir, "pong"), "0"],
                            stdout=subprocess.PIPE)
    _CHILDREN.append(pong)
    pport = json.loads(pong.stdout.readline())["listening"]
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    hport = s.getsockname()[1]
    s.close()
    httpd = subprocess.Popen(
        [os.path.join(ndir, "httpd"), str(hport), ring_path, "127.0.0.1",
         str(pport)], stdout=subprocess.PIPE)
    _CHILDREN.append(httpd)
    httpd.stdout.readline()
    time.sleep(0.3)
    try:
        lg_bin = os.path.join(ndir, "loadgen_http")
        # Warm the jitted lane program off the measurement run.
        _run_tracked([lg_bin, str(hport), "8192", "1024", "100"],
                     capture_output=True, timeout=300)
        out = _run_tracked(
            [lg_bin, str(hport), str(n_requests), "4096", "100"],
            capture_output=True, text=True, timeout=300)
        res = json.loads(out.stdout.strip())
        # The native plane's own counters explain the block/fail-open
        # split: behind a slow transport, verdicts that miss the 3 s
        # deadline fail open (attacks pass rather than stall), so
        # e2e_blocked alone under-reports the WAF (e2e_fail_open says
        # how many requests the timeout released).
        stats = _scrape_metrics_json(hport)
        # Per-stage sidecar latency + shm ring telemetry: the registry
        # snapshot rides the artifact so a perf run carries its own
        # stage breakdown (queue/encode/dispatch/compute/post).
        from pingoo_tpu.obs import REGISTRY

        stage_latency = REGISTRY.stage_snapshot()
        ring_tel = sidecar.ring_telemetry()
    finally:
        pong.kill()
        httpd.kill()
        sidecar.stop()
        ring.close()
    p50, p99 = _hist_percentiles(stats.get("verdict_wait_ms_hist"))
    return {
        "e2e_stage_latency": stage_latency,
        "e2e_ring_telemetry": ring_tel,
        "e2e_req_per_s": res["req_per_s"],
        "e2e_added_p50_ms": res["p50_ms"],
        "e2e_added_p99_ms": res["p99_ms"],
        "serving_p50_ms_le": p50,
        "serving_p99_ms_le": p99,
        "e2e_completed": res["completed"],
        "e2e_blocked": res["blocked"],
        "e2e_fail_open": stats.get("fail_open"),
        "e2e_verdicts": stats.get("verdicts"),
        "e2e_errors": res["errors"],
        "e2e_note": ("verdict device reached through a network tunnel in "
                     "this environment; e2e latency/throughput are "
                     "dominated by per-batch tunnel transfers, not chip "
                     "or data-plane capability; verdicts missing the "
                     "native plane's 3 s deadline fail open, so blocked "
                     "counts only verdicts that beat the tunnel"),
    }


def _scrape_metrics_json(port: int) -> dict:
    """Scrape /__pingoo/metrics in its JSON form. The endpoint now
    content-negotiates (Prometheus text by default, ISSUE 2), so the
    legacy-schema consumer must ask for application/json explicitly."""
    try:
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/__pingoo/metrics",
            headers={"accept": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as resp:
            return json.loads(resp.read())
    except Exception:
        return {}


def _hist_percentiles(hist):
    """(p50, p99) upper bounds from the data plane's enqueue->verdict
    wall-time histogram (httpd.cc verdict_wait_ms_hist) — the serving-
    path latency the <2 ms budget is about; kernel time alone cannot
    see ring/batching/transport waits. ">100" for the unbounded bucket:
    Infinity is not valid JSON and would break the driver's parse."""
    if not hist:
        return None, None
    edges = [("le1", 1.0), ("le2", 2.0), ("le5", 5.0), ("le10", 10.0),
             ("le50", 50.0), ("le100", 100.0), ("inf", float("inf"))]
    total = sum(hist.get(k, 0) for k, _ in edges)
    if not total:
        return None, None

    def pct(q):
        need = q * total
        run = 0
        for k, edge in edges:
            run += hist.get(k, 0)
            if run >= need:
                return edge if edge != float("inf") else ">100"
        return ">100"

    return pct(0.50), pct(0.99)


def bench_dataplane(n_requests: int = 200_000) -> dict:
    """Data-plane capacity with the DEVICE OUT OF THE LOOP: loadgen_http
    -> native httpd -> shared-memory ring -> NATIVE canned-verdict drain
    (native/drain.cc: memmem content check + batched verdict post; no
    accelerator, no tunnel, no Python in the loop) -> 403/proxy -> pong.
    This isolates the non-chip half of the serving path, which the
    tunnel-bound e2e number cannot see: it answers whether the C++
    plane + ring transport can carry the request rates the chip can
    verdict (VERDICT r2 item 2; r3 item 5 moved the drain native)."""
    import tempfile

    from pingoo_tpu import native_ring
    from pingoo_tpu.native_ring import Ring

    if not native_ring.ensure_built():
        return {"dataplane_note": "native toolchain unavailable"}
    ndir = native_ring.NATIVE_DIR
    _run_tracked(["make", "-C", ndir, "httpd", "pong", "loadgen_http",
                  "drain"], check=True, capture_output=True)

    # Defaults tuned for THIS 1-CPU host (nproc == 1): one worker and
    # c=128 measured fastest (~23k req/s, p99 <= 10 ms with the native
    # drain; the old Python drain measured 14.1k); more workers just
    # time-share the core. On a multi-core host raise BENCH_DP_WORKERS /
    # BENCH_DP_LOADGENS to exercise the SO_REUSEPORT + ring-per-worker
    # sharding this bench is built on.
    workers = int(os.environ.get("BENCH_DP_WORKERS", "1"))
    loadgens = int(os.environ.get("BENCH_DP_LOADGENS", "1"))
    tmp = tempfile.mkdtemp(prefix="pingoo-dpbench-")
    rings = [Ring(os.path.join(tmp, f"ring{i}"), capacity=16384, create=True)
             for i in range(workers)]
    # Native drain process: C++ memmem + batched verdict post over all
    # worker rings (one consumer: the request queue pop is destructive
    # and the scratch batch is per-process).
    drain = subprocess.Popen(
        [os.path.join(ndir, "drain")]
        + [os.path.join(tmp, f"ring{i}") for i in range(workers)],
        stdout=subprocess.PIPE)
    _CHILDREN.append(drain)
    assert b"draining" in drain.stdout.readline()
    pong = subprocess.Popen([os.path.join(ndir, "pong"), "0"],
                            stdout=subprocess.PIPE)
    _CHILDREN.append(pong)
    pport = json.loads(pong.stdout.readline())["listening"]
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    hport = s.getsockname()[1]
    s.close()
    # N workers share the port via SO_REUSEPORT (the kernel load-
    # balances accepted connections), each with its own verdict ring —
    # the per-core sharding a production deployment uses (verdicts must
    # return on the worker's own ring: the verdict queue is MPMC, so
    # co-consuming workers would steal each other's tickets).
    httpds = []
    for i in range(workers):
        h = subprocess.Popen(
            [os.path.join(ndir, "httpd"), str(hport),
             os.path.join(tmp, f"ring{i}"), "127.0.0.1", str(pport)],
            stdout=subprocess.PIPE)
        _CHILDREN.append(h)
        h.stdout.readline()
        httpds.append(h)
    time.sleep(0.2)
    try:
        lg_bin = os.path.join(ndir, "loadgen_http")
        _run_tracked([lg_bin, str(hport), "8192", "256", "100"],
                     capture_output=True, timeout=120)  # warm-up
        per_lg = n_requests // loadgens
        conc = int(os.environ.get("BENCH_DP_CONC", "128")) // loadgens
        procs = [subprocess.Popen(
            [lg_bin, str(hport), str(per_lg), str(conc), "100"],
            stdout=subprocess.PIPE, text=True) for _ in range(loadgens)]
        _CHILDREN.extend(procs)
        results = []
        for p in procs:
            out, _ = p.communicate(timeout=300)
            results.append(json.loads(out.strip()))
        dp_stats = _scrape_metrics_json(hport)
    finally:
        drain.terminate()
        try:
            drain.wait(timeout=10)
        except subprocess.TimeoutExpired:
            drain.kill()
        pong.kill()
        for h in httpds:
            h.kill()
        for ring in rings:
            ring.close()
    completed = sum(r["completed"] for r in results)
    elapsed = max(r["elapsed_s"] for r in results)
    # The metrics scrape lands on ONE SO_REUSEPORT worker; with several
    # workers its histogram covers only that worker's share, so the
    # serving percentiles are only published when they describe the
    # whole plane (workers == 1).
    dp50 = dp99 = None
    if workers == 1:
        dp50, dp99 = _hist_percentiles(
            dp_stats.get("verdict_wait_ms_hist"))
    return {
        "dataplane_req_per_s": round(completed / elapsed, 1),
        "dataplane_serving_p50_ms_le": dp50,
        "dataplane_serving_p99_ms_le": dp99,
        "dataplane_p50_ms": round(
            sum(r["p50_ms"] for r in results) / len(results), 3),
        "dataplane_p99_ms": round(max(r["p99_ms"] for r in results), 3),
        "dataplane_completed": completed,
        "dataplane_blocked": sum(r["blocked"] for r in results),
        "dataplane_errors": sum(r["errors"] for r in results),
        "dataplane_workers": workers,
        "dataplane_note": (
            "device out of the loop (canned verdicts): loadgen -> C++ "
            "httpd workers (SO_REUSEPORT, one verdict ring each) -> ring "
            "-> NATIVE drain (native/drain.cc) -> proxy/403; no Python "
            "anywhere in the loop. LIMIT ANALYSIS: this host has ONE "
            "cpu (nproc=1); loadgen + httpd + drain + upstream "
            "time-share it, so the absolute number is the single-core "
            "harness ceiling — per-core sharding (SO_REUSEPORT + one "
            "verdict ring per worker) is in place and scales with cores "
            "on real hosts"),
    }


def _probe_backend(retries: int = None, timeout_s: int = None):
    """Initialize the jax backend in a SUBPROCESS with a bounded retry.

    Round 3's bench called jax.devices() bare and died rc=1 when the
    tunneled TPU transport was wedged, leaving the driver with
    parsed=null (BENCH_r03.json). A wedged axon backend can also HANG
    inside init rather than raise, so the probe must be a subprocess
    with a timeout — an in-process try/except guards neither failure
    mode. Returns (ok, info_string)."""
    if retries is None:
        retries = int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
    if timeout_s is None:
        timeout_s = int(os.environ.get("BENCH_PROBE_TIMEOUT", "60"))
    from __graft_entry__ import JAX_PLATFORM_SHIM

    code = (JAX_PLATFORM_SHIM +
            "d = jax.devices()\nprint(d[0].platform, len(d))\n")
    last = ""
    for attempt in range(retries):
        try:
            p = _run_tracked([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
            if p.returncode == 0 and p.stdout.strip():
                return True, p.stdout.strip()
            last = (p.stderr or "").strip()[-300:] or f"rc={p.returncode}"
        except subprocess.TimeoutExpired:
            last = f"backend init timed out after {timeout_s}s"
        except Exception as exc:
            last = repr(exc)[:300]
        if attempt < retries - 1:
            time.sleep(5)
    return False, last


_CHILDREN: list = []  # every child process, so the watchdog can reap them

# Exactly ONE result line ever reaches stdout, no matter which thread
# (main, watchdog) wins: the driver parses the last line, and two racing
# print() calls can interleave their write()s into an unparseable blob.
_EMIT_LOCK = threading.Lock()
_EMITTED = False


def _history_enabled() -> bool:
    return "--history" in sys.argv or os.environ.get("BENCH_HISTORY") == "1"


def _history_path() -> str:
    return os.environ.get("BENCH_HISTORY_FILE", "BENCH_history.jsonl")


_GIT_COMMIT: list = []  # one-shot cache: [] = unprobed, [str|None] = probed


def _git_commit():
    """Best-effort short commit hash for history provenance; None when
    git/tree is unavailable (history append must never fail the run)."""
    if not _GIT_COMMIT:
        commit = None
        try:
            out = subprocess.run(
                ["git", "rev-parse", "--short", "HEAD"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                timeout=5, cwd=os.path.dirname(os.path.abspath(__file__)))
            if out.returncode == 0:
                commit = out.stdout.decode().strip() or None
        except Exception:
            commit = None
        _GIT_COMMIT.append(commit)
    return _GIT_COMMIT[0]


def _append_history(line: str) -> None:
    """Bench trajectory (ISSUE 5 satellite): append THE emitted result
    line (success or error — a failed run is trajectory too) to
    BENCH_history.jsonl with a wall-clock stamp, so
    tools/bench_regress.py can diff consecutive runs. Best-effort: a
    read-only tree must not turn a finished bench into rc=1.

    ISSUE 17 satellite: every line also carries a history schema
    version, the backend, and the git commit, so bench_regress.py can
    refuse cross-backend comparisons explicitly instead of silently
    diffing a CPU run against a TPU baseline."""
    try:
        entry = {"ts": round(time.time(), 3), **json.loads(line)}
        entry.setdefault("history_schema", 2)
        entry.setdefault("backend", os.environ.get("PINGOO_BENCH_BACKEND",
                                                   "unknown"))
        commit = _git_commit()
        if commit:
            entry.setdefault("git_commit", commit)
        with open(_history_path(), "a") as f:
            f.write(json.dumps(entry) + "\n")
    except Exception:
        pass


def _emit_once(line: str) -> bool:
    global _EMITTED
    with _EMIT_LOCK:
        if _EMITTED:
            return False
        _EMITTED = True
        print(line, flush=True)
        if _history_enabled():
            _append_history(line)
        return True


def _run_tracked(argv, capture_output=False, text=None, timeout=None,
                 check=False, **kw):
    """Like subprocess.run, but the child is registered in _CHILDREN for
    the watchdog: a watchdog os._exit during an in-flight run() would
    otherwise orphan the child (probe shims, make, loadgen runs)."""
    if capture_output:
        kw["stdout"] = subprocess.PIPE
        kw["stderr"] = subprocess.PIPE
    p = subprocess.Popen(argv, text=text, **kw)
    _CHILDREN.append(p)
    try:
        out, err = p.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        p.kill()
        p.communicate()
        raise
    if check and p.returncode != 0:
        raise subprocess.CalledProcessError(p.returncode, argv, out, err)
    return subprocess.CompletedProcess(argv, p.returncode, out, err)


def _emit_error_line(result: dict, error: str) -> None:
    """The driver must ALWAYS get one parseable JSON line, even when the
    device is unreachable or the run dies mid-way: emit whatever partial
    results exist plus the error."""
    out = {
        "metric": "waf_requests_per_sec_per_chip_500rules",
        "value": 0,
        "unit": "req/s",
        "vs_baseline": 0.0,
    }
    try:
        out.update(dict(result))
        out["error"] = error[:500]
        line = json.dumps(out)
    except Exception:
        # The main thread may be mutating `result` mid-copy; a partial
        # snapshot is not worth losing the line over.
        line = json.dumps({
            "metric": "waf_requests_per_sec_per_chip_500rules",
            "value": 0, "unit": "req/s", "vs_baseline": 0.0,
            "error": error[:500],
        })
    _emit_once(line)


def main() -> int:
    # NOTHING runs outside this guard: env parsing, the __graft_entry__
    # import, the probe — any exception anywhere must still yield the
    # one JSON line (round 3's parsed=null came from an unguarded
    # crash).
    result: dict = {}
    try:
        return _main_guarded(result)
    except Exception as exc:
        _emit_error_line(result, repr(exc))
        return 1


def _main_guarded(result: dict) -> int:
    # Watchdog: if anything later (device transfer, e2e subprocess, ...)
    # wedges past the deadline, print the partial-result error line and
    # hard-exit — the driver records a parsed line instead of a timeout.
    deadline_s = int(os.environ.get("BENCH_WATCHDOG_S", "2400"))
    done = threading.Event()

    def _watchdog():
        if not done.wait(deadline_s):
            if done.is_set() or _EMITTED:
                return  # main finished right at the deadline: not a hang
            try:
                _emit_error_line(result,
                                 f"bench watchdog fired after {deadline_s}s; "
                                 f"partial results only")
                for child in _CHILDREN:  # do not orphan native processes
                    try:
                        if child.poll() is None:
                            child.kill()
                    except Exception:
                        pass
            finally:
                os._exit(2)

    threading.Thread(target=_watchdog, daemon=True).start()

    ok, info = _probe_backend()
    if not ok:
        # VERDICT r4 item 4: the artifact must still carry a NUMBER.
        # The accelerator transport is unreachable (this environment's
        # tunneled chip has been observed wedged for whole rounds), so
        # run the SAME pipeline on the CPU XLA backend, clearly labeled:
        # `backend: "cpu-diagnostic"` + the preflight failure. The
        # number is a diagnostic floor (host CPU, one core pool), NOT
        # the chip capability — consumers must branch on `backend`.
        result["backend"] = "cpu-diagnostic"
        result["backend_probe_error"] = info[:300]
        os.environ["JAX_PLATFORMS"] = "cpu"
        # CPU runs the verdict ~2 orders slower: shrink the timed loops
        # so the diagnostic completes well inside the watchdog.
        os.environ.setdefault("BENCH_ITERS", "10")
        os.environ.setdefault("BENCH_SKIP_BLOCKLIST", "1")
        os.environ.setdefault("BENCH_SKIP_E2E", "1")
        # The dataplane bench is DEVICE-INDEPENDENT (native drain, no
        # accelerator in the loop): run it FIRST so the artifact
        # carries a real native-plane measurement even if the CPU XLA
        # pipeline below also fails on this degraded host (the error
        # line includes every partial result).
        if os.environ.get("BENCH_SKIP_DATAPLANE") != "1":
            try:
                result.update(bench_dataplane())
            except Exception as exc:
                result["dataplane_error"] = repr(exc)[:200]
            os.environ["BENCH_SKIP_DATAPLANE"] = "1"  # ran already
    else:
        result["backend"] = "device"
        result["backend_probe"] = info
    try:
        _main_impl(result, done)
    except Exception as exc:
        done.set()
        _emit_error_line(result, repr(exc))
        return 1
    finally:
        done.set()
    return 0


def _main_impl(result: dict, done=None) -> None:
    # 2048 keeps the full-batch verdict inside the 2 ms latency budget on
    # a v5e-1 while giving up only ~5% throughput vs 4096.
    batch_size = int(os.environ.get("BENCH_BATCH", "2048"))
    num_rules = int(os.environ.get("BENCH_RULES", "500"))
    iters = int(os.environ.get("BENCH_ITERS", "200"))

    from __graft_entry__ import apply_jax_platform_env

    apply_jax_platform_env()
    import jax
    import jax.numpy as jnp

    from pingoo_tpu.compiler import compile_ruleset
    from pingoo_tpu.engine import encode_requests
    from pingoo_tpu.engine.batch import bucket_arrays
    from pingoo_tpu.engine.verdict import _eval_bool, _eval_leaves
    from pingoo_tpu.utils.crs import generate_ruleset, generate_traffic

    dev = jax.devices()[0]
    t0 = time.time()
    rules, lists = generate_ruleset(
        num_rules, with_lists=True, list_sizes=(131072, 4096))
    plan = compile_ruleset(rules, lists)
    build_s = time.time() - t0
    residency = plan.stats["device_rules"] / plan.stats["rules"]
    device_rules = [r for r in plan.rules if not r.host]

    tables = jax.device_put(plan.device_tables(), dev)
    reqs = generate_traffic(batch_size, lists=lists, seed=100)
    arrays = jax.device_put(bucket_arrays(encode_requests(reqs).arrays), dev)

    def verdict_body(tables, arrays, salt):
        B = arrays["asn"].shape[0]
        a = dict(arrays)
        # Salt EVERY input column so no per-batch work is loop-invariant:
        # XLA's while-loop code motion hoists computations whose inputs
        # don't change across iterations, and an asn-only salt (the r1/r2
        # bench) let it hoist the NFA scans — the dominant cost — out of
        # the timed loop, overstating throughput ~2x. With the byte
        # tensors and numeric columns all salted by the carried checksum,
        # every iteration re-runs the full verdict. The salt itself mixes
        # the LOOP INDEX in (see run_n): a checksum-parity-only salt can
        # stick at 0 when the match count stays even, which would make
        # the inputs invariant after all.
        a["asn"] = a["asn"] + salt
        for k in list(a):
            if k.endswith("_bytes"):
                a[k] = a[k] ^ salt.astype(jnp.uint8)
            elif k != "asn" and not k.endswith("_len") and \
                    jnp.issubdtype(a[k].dtype, jnp.integer):
                a[k] = a[k] + salt.astype(a[k].dtype)
        leaves = _eval_leaves(plan, tables, a, B)
        eff = [None] * len(plan.leaves)
        for leaf_id, (v, e) in leaves.items():
            eff[leaf_id] = v & ~e
        base = eff + [jnp.ones((B,), dtype=bool), jnp.zeros((B,), dtype=bool)]
        extra, rule_col = [], []
        from pingoo_tpu.compiler.lowering import BConst, BErrConst, BLeaf

        for rule in device_rules:
            if rule.always:
                rule_col.append(len(plan.leaves))
            elif isinstance(rule.ir, BLeaf):
                rule_col.append(rule.ir.leaf_id)
            elif isinstance(rule.ir, BConst):
                rule_col.append(len(plan.leaves) if rule.ir.value
                                else len(plan.leaves) + 1)
            elif isinstance(rule.ir, BErrConst):
                rule_col.append(len(plan.leaves) + 1)
            else:
                v, e = _eval_bool(rule.ir, leaves, B)
                rule_col.append(len(base) + len(extra))
                extra.append(v & ~e)
        allmat = jnp.stack(base + extra, axis=1)
        return jnp.take(allmat, jnp.asarray(rule_col, dtype=jnp.int32), axis=1)

    @jax.jit
    def run_n(tables, arrays, n):
        def body(i, acc):
            m = verdict_body(tables, arrays, (acc + i) % 2)
            return acc + m.sum().astype(jnp.int64)
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    @jax.jit
    def floor_loop(arrays, n):
        def body(i, acc):
            return acc + arrays["asn"].sum() + i
        return jax.lax.fori_loop(0, n, body, jnp.int64(0))

    t0 = time.time()
    int(run_n(tables, arrays, 2))
    int(floor_loop(arrays, 2))
    compile_s = time.time() - t0

    t0 = time.time()
    int(floor_loop(arrays, iters))
    floor_a = time.time() - t0
    t0 = time.time()
    checksum = int(run_n(tables, arrays, iters))
    full = time.time() - t0
    t0 = time.time()
    int(floor_loop(arrays, iters))
    floor_b = time.time() - t0

    per_batch_s = (full - (floor_a + floor_b) / 2) / iters
    rps = batch_size / per_batch_s
    result.update({
        "metric": "waf_requests_per_sec_per_chip_500rules",
        "value": round(rps, 1),
        "unit": "req/s",
        "vs_baseline": round(rps / 1_000_000.0, 4),
        "batch_size": batch_size,
        "rules": num_rules,
        "device_rules": plan.stats["device_rules"],
        "device_residency": round(residency, 4),
        "p_batch_ms": round(per_batch_s * 1000, 3),
        "latency_budget_ms": 2.0,
        "device": str(dev),
        "checksum": checksum,
        "build_s": round(build_s, 1),
        "compile_s": round(compile_s, 1),
    })
    # Literal-prefilter cascade (ISSUE 4): per-mode throughput + Stage-A
    # candidate stats; the fastest mode becomes the plan's default and
    # rides the artifact cache like the scan-strategy autotune below.
    if os.environ.get("BENCH_SKIP_PREFILTER") != "1":
        try:
            pf_res = bench_prefilter_modes(
                plan, tables, arrays, verdict_body,
                iters=min(iters, int(os.environ.get(
                    "BENCH_PREFILTER_ITERS", "30"))))
            result["prefilter"] = pf_res
            cache_dir = os.environ.get("PINGOO_CACHE_DIR")
            if cache_dir and pf_res.get("selected"):
                from pingoo_tpu.compiler.cache import update_cached_plan

                update_cached_plan(rules, lists, plan, cache_dir)
        except Exception as exc:
            result["prefilter_error"] = repr(exc)[:200]
    # Bitsplit-DFA lowering (ISSUE 8): off/auto/force A/B over the PR 4
    # compact baseline; the fastest mode becomes the plan's default and
    # rides the artifact cache like the prefilter selection above.
    if "--dfa" in sys.argv or os.environ.get("BENCH_SKIP_DFA") != "1":
        try:
            dfa_res = bench_dfa_modes(
                plan, tables, arrays, verdict_body,
                iters=min(iters, int(os.environ.get(
                    "BENCH_DFA_ITERS", "30"))))
            result["dfa"] = dfa_res
            auto_rps = dfa_res["modes"].get("auto", {}).get("req_per_s")
            if auto_rps:
                result["dfa_auto_req_per_s"] = auto_rps
            cache_dir = os.environ.get("PINGOO_CACHE_DIR")
            if cache_dir and dfa_res.get("selected"):
                from pingoo_tpu.compiler.cache import update_cached_plan

                update_cached_plan(rules, lists, plan, cache_dir)
        except Exception as exc:
            result["dfa_error"] = repr(exc)[:200]
    # Micro-autotune: replace the plan's default cost-model strategy
    # selection with MEASURED per-iteration costs, and persist the tuned
    # plan into the artifact cache when one is configured — runs on a
    # real device backend by default (the CPU backend inverts the
    # relative costs; BENCH_AUTOTUNE=force measures anyway, =0 skips).
    autotune = os.environ.get("BENCH_AUTOTUNE", "auto")
    if autotune != "0" and (result.get("backend") == "device"
                            or autotune == "force"):
        try:
            from pingoo_tpu.compiler.plan import reselect_scan_strategies

            costs = autotune_scan_strategies(plan, tables, arrays)
            if costs:
                reselect_scan_strategies(plan, costs)
                result["autotune_costs"] = {
                    k: round(v, 4) for k, v in costs.items()}
                result["autotune_selected"] = {
                    k: e.strategy.kind + ("+pair" if e.strategy.pair else "")
                    for k, e in plan.scan_plans.items()}
                cache_dir = os.environ.get("PINGOO_CACHE_DIR")
                if cache_dir:
                    from pingoo_tpu.compiler.cache import update_cached_plan

                    update_cached_plan(rules, lists, plan, cache_dir)
        except Exception as exc:
            result["autotune_error"] = repr(exc)[:200]
    # Scheduler-mode + serving-mesh A/B (ISSUE 6): runs when --mesh
    # dpxtpxsp (or BENCH_MESH) is given, or under BENCH_SCHED=1 for the
    # single-device scheduler comparison alone. Subprocess-isolated so
    # the forced virtual-device count never touches this process.
    mesh_spec = _mesh_arg()
    if mesh_spec is None and os.environ.get("BENCH_SCHED") == "1":
        mesh_spec = "1x1x1"
    if mesh_spec is not None and os.environ.get("BENCH_SKIP_SCHED") != "1":
        try:
            result.update(bench_sched(mesh_spec))
        except Exception as exc:
            result["sched_error"] = repr(exc)[:200]
    # Zero-copy pipelined executor A/B (ISSUE 9): PINGOO_PIPELINE
    # off vs on over the same ring-driven traffic, identical-verdict-
    # checksum enforced. Subprocess-isolated like the sched bench.
    if ("--pipeline" in sys.argv
            or os.environ.get("BENCH_SKIP_PIPELINE") != "1"):
        try:
            result.update(bench_pipeline())
        except Exception as exc:
            result["pipeline_error"] = repr(exc)[:200]
    # Compact staging A/B (ISSUE 15): PINGOO_STAGING full vs compact
    # over the same long-URL-tail ring traffic, identical-verdict-
    # checksum asserted. Subprocess-isolated like the pipeline bench.
    if ("--staging" in sys.argv
            or os.environ.get("BENCH_SKIP_STAGING") != "1"):
        try:
            result.update(bench_staging())
        except Exception as exc:
            result["staging_error"] = repr(exc)[:200]
    # Streaming body-scan arm (ISSUE 13): interleaved multi-flow window
    # streams vs the contiguous one-shot over identical payloads, with
    # verdict equality (and the interpreter oracle) enforced.
    if "--body" in sys.argv or os.environ.get("BENCH_SKIP_BODY") != "1":
        try:
            result.update(bench_body())
        except Exception as exc:
            result["body_error"] = repr(exc)[:200]
    if os.environ.get("BENCH_SKIP_BLOCKLIST") != "1":
        try:
            result.update(bench_blocklist_1m())
        except Exception as exc:  # a failing side-bench must not kill the line
            result["blocklist_error"] = repr(exc)[:200]
    if os.environ.get("BENCH_SKIP_E2E") != "1":
        try:
            result.update(bench_e2e(plan, lists))
        except Exception as exc:
            result["e2e_error"] = repr(exc)[:200]
    if os.environ.get("BENCH_SKIP_DATAPLANE") != "1":
        try:
            result.update(bench_dataplane())
        except Exception as exc:
            result["dataplane_error"] = repr(exc)[:200]
    try:
        # Whole-run stage-latency snapshot (ISSUE 2): whatever verdict
        # pipeline stages ran in-process (the e2e sidecar, any engine
        # warm-up) ride the artifact for offline breakdowns.
        from pingoo_tpu.obs import REGISTRY

        stages = REGISTRY.stage_snapshot()
        if stages:
            result["stage_latency"] = stages
    except Exception:
        pass
    if done is not None:
        done.set()
    # The emit-once gate, not print(): a watchdog that timed out a
    # microsecond before done.set() must not interleave with this line.
    _emit_once(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
