# Container image (reference parity: multi-stage Dockerfile; the
# reference builds the captcha frontend then a static Rust binary into a
# scratch image. Ours needs the Python/JAX runtime, so the final stage is
# a slim python base with the native ring built in-stage.)
#
# The geoip database is expected at /etc/pingoo/geoip.mmdb[.zst]
# (mounted or copied at deploy time, as in the reference's image which
# fetches geoip.mmdb.zst at build).

FROM python:3.12-slim AS build
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make && rm -rf /var/lib/apt/lists/*
WORKDIR /src
COPY pyproject.toml README.md Makefile .clang-tidy ./
COPY pingoo_tpu ./pingoo_tpu
COPY tools ./tools
COPY docs ./docs
# Build the native plane, then gate the image on the static-analysis
# suite (ABI layout parity, hot-path lint, TSAN ring stress, metrics
# schema — docs/STATIC_ANALYSIS.md); clang-tidy skips with a warning
# in this slim stage.
RUN pip install --no-cache-dir numpy && \
    make -C pingoo_tpu/native && make analyze && \
    pip wheel --no-deps -w /wheels .

FROM python:3.12-slim
RUN useradd -r -u 10001 pingoo && mkdir -p /etc/pingoo/tls && \
    chown -R pingoo /etc/pingoo
COPY --from=build /wheels /wheels
RUN pip install --no-cache-dir /wheels/*.whl "jax[cpu]" && rm -rf /wheels
# TPU deployments: swap the jax extra for the libtpu wheel of the target
# runtime (e.g. pip install jax[tpu] -f https://storage.googleapis.com/jax-releases/libtpu_releases.html)
USER pingoo
EXPOSE 80 443
ENTRYPOINT ["python", "-m", "pingoo_tpu"]
